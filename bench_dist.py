"""Distributed scaling-efficiency + sparse-crossover harness.

Measures (SURVEY.md §5.8, §6; BASELINE north-star "≥90% scaling
efficiency over ICI"):

  1. **Scaling efficiency** — W-chip DistOpt throughput vs W × 1-chip
     throughput at identical per-chip batch
     (``utils.metrics.scaling_efficiency``).
  2. **Dense vs top-K sparse wire-cost crossover** — per-step time of
     ``backward_and_sparse_update`` at K ∈ {0.5%, 1%, 5%} against dense
     ``backward_and_update`` (the reference could claim but never measure
     this; SURVEY.md §5.8: "measure both, report which wins at which K").
  3. **Partial-update conditional-collective proof** — the 1/W wire-cost
     claim of ``backward_and_partial_update`` holds only if XLA keeps the
     ``lax.cond`` around the psum as a real conditional; the compiled
     step's HLO is inspected for all-reduces nested in conditionals.

On the 1-TPU dev box this runs on a virtual W-device CPU mesh
(self-provisioned like __graft_entry__): the efficiency numbers then
validate the harness + sharding, not ICI — the JSON artifact records
which backend produced them.  On a real multi-chip TPU the same command
is the ≥90% evidence.

    python bench_dist.py --world 8 --out SCALING.json
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
_CHILD = "_BENCH_DIST_CHILD"


def _provision_or_reexec(world):
    import __graft_entry__ as ge

    if os.environ.get(_CHILD) == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
        assert len(jax.devices()) >= world
        return True
    import jax

    if len(jax.devices()) >= world:
        return True
    env = dict(os.environ)
    env["XLA_FLAGS"] = ge._force_host_device_count(
        env.get("XLA_FLAGS", ""), world)
    env[_CHILD] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    rc = subprocess.run([sys.executable, os.path.abspath(__file__)]
                        + sys.argv[1:], env=env, cwd=_REPO).returncode
    sys.exit(rc)


def _build(world, batch_per_chip, model_name, dist, seed=0):
    import jax

    from singa_tpu import device, opt, tensor
    from singa_tpu.parallel.communicator import Communicator, get_mesh
    from singa_tpu.parallel.dist_opt import DistOpt

    dev = device.TpuDevice(0, jax.devices()[0])
    dev.SetRandSeed(seed)
    if model_name == "resnet18":
        from singa_tpu.models.resnet import resnet18

        m = resnet18(num_classes=10)
        shape = (3, 32, 32)
    else:
        from singa_tpu.models.cnn import CNN

        m = CNN(num_classes=10, num_channels=1)
        shape = (1, 28, 28)
    sgd = opt.SGD(lr=0.005, momentum=0.9)
    if dist:
        sgd = DistOpt(sgd, communicator=Communicator(
            mesh=get_mesh(num_devices=world)))
    m.set_optimizer(sgd)
    batch = batch_per_chip * (world if dist else 1)
    rng = np.random.RandomState(seed)
    x = tensor.from_numpy(
        rng.randn(batch, *shape).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 10, (batch,)).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)
    return m, x, y, batch


def _time_steps(m, x, y, iters, **kw):
    m(x, y, **kw)          # eager warm
    m(x, y, **kw)          # compile
    _, loss = m(x, y, **kw)
    float(loss.data)
    t0 = time.time()
    for _ in range(iters):
        _, loss = m(x, y, **kw)
    float(loss.data)
    return (time.time() - t0) / iters


def _hlo_of(m):
    """HLO text of the (single) compiled step executable."""
    for fn, _names, _cost in m._graph_runner._compiled.values():
        try:
            return fn.as_text()
        except AttributeError:
            continue
    return ""


def _step_flops(m):
    """XLA cost-analysis FLOPs of the compiled step (0 if unavailable)."""
    for _fn, _names, cost in m._graph_runner._compiled.values():
        if cost and cost.get("flops"):
            return float(cost["flops"])
    return 0.0


def _count_ops(hlo, opcode):
    """Count HLO INSTRUCTIONS of an opcode, not substring hits: an
    instruction's default name repeats its opcode ('%all-reduce.3 =
    ... all-reduce(...)') and operand references repeat it again, so a
    plain .count() overstates several-fold.  An opcode occurrence is
    ' opcode(' on the rhs of an assignment (incl. async -start
    variants; '-done' is the other half of the same op, not counted)."""
    import re

    return len(re.findall(rf"= [^\n=]*\s{re.escape(opcode)}(?:-start)?\(",
                          hlo))


def _hlo_computations(hlo):
    """name -> computation body text.  Computations start at column 0
    with ``%name (params) -> type {`` (or ``ENTRY %name ...``) and end
    at a column-0 ``}``."""
    import re

    comps = {}
    name, lines = None, []
    for line in hlo.splitlines():
        m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{", line)
        if m and not line.startswith(" "):
            name, lines = m.group(1), [line]
        elif name is not None:
            lines.append(line)
            if line.startswith("}"):
                comps[name] = "\n".join(lines)
                name, lines = None, []
    return comps


def _conditional_allreduce_stats(hlo):
    """How many all-reduces sit inside conditional branch computations
    vs top-level.  HLO conditionals name their branches in attributes
    (``branch_computations={%a, %b}`` or ``true_computation=%t,
    false_computation=%f``); XLA/GSPMD gives the computations themselves
    opaque names like ``%region_16.18_spmd``, so membership must be
    resolved by following those attribute references (plus the
    transitive ``to_apply=``/``body=``/nested-branch calls), not by
    grepping computation headers for 'branch'/'cond' — round-2 verdict:
    the name-grep never matched and reported 0 against a true claim.
    A branch-local all-reduce proves the collective only executes on
    its turn (the 1/W wire claim)."""
    import re

    total = _count_ops(hlo, "all-reduce")
    n_cond = _count_ops(hlo, "conditional")
    comps = _hlo_computations(hlo)

    # seed: every computation named in a conditional's branch attributes
    seed = set()
    for m in re.finditer(r"branch_computations=\{([^}]*)\}", hlo):
        seed.update(n.strip().lstrip("%") for n in m.group(1).split(","))
    for m in re.finditer(
            r"(?:true_computation|false_computation)=%([\w.\-]+)", hlo):
        seed.add(m.group(1))

    # transitive closure over computations called from a branch
    callee_re = re.compile(
        r"(?:to_apply|body|condition|true_computation|false_computation)"
        r"=%([\w.\-]+)")
    in_branch, frontier = set(), set(n for n in seed if n in comps)
    while frontier:
        n = frontier.pop()
        in_branch.add(n)
        body = comps[n]
        callees = set(callee_re.findall(body))
        for m in re.finditer(r"branch_computations=\{([^}]*)\}", body):
            callees.update(c.strip().lstrip("%")
                           for c in m.group(1).split(","))
        frontier |= {c for c in callees if c in comps} - in_branch
    in_branches = sum(_count_ops(comps[n], "all-reduce")
                      for n in in_branch)
    return {"all_reduce_total": total, "conditional_ops": n_cond,
            "all_reduce_in_cond_branches": in_branches}


def _collective_bytes(hlo, opcode):
    """Sum output bytes over instructions of a collective opcode
    (tuple-shaped fused variants included)."""
    import re

    sizes = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8,
             "s32": 4, "u64": 8, "u32": 4, "s16": 2, "u16": 2,
             "s8": 1, "u8": 1, "pred": 1}
    total = 0
    for m in re.finditer(
            rf"= ([^\n=]*?)\s{re.escape(opcode)}(?:-start)?\(", hlo):
        for dt, dims in re.findall(r"([a-z]\w*)\[([\d,]*)\]", m.group(1)):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * sizes.get(dt, 4)
    return total


# v5e per-chip ICI: 4 links in a 2D torus; a ring all-reduce streams on
# one link pair per direction at ~45 GB/s/link/direction.  These are
# ASSUMED public-spec constants for the projection, recorded in the
# artifact so the arithmetic is reproducible (no multi-chip hardware
# here to measure — SURVEY.md §6).
_ICI_BW = 9.0e10          # bytes/s effective one-direction ring bandwidth
_V5E_PEAK_BF16 = 1.97e14  # FLOP/s
_ASSUMED_MFU = 0.28       # measured conv-net MFU (BENCH resnet50)


def _ici_projection(hlo_dense, step_flops, W):
    """Analytic bridge to the >=90% ICI target: per-step all-reduce
    bytes from the HLO x assumed v5e ICI bandwidth vs projected compute
    time -> projected W-chip scaling efficiency.  Backend-independent
    (the virtual-CPU-mesh *timings* say nothing about ICI; the HLO
    byte counts do)."""
    ar_bytes = _collective_bytes(hlo_dense, "all-reduce")
    # ring all-reduce per-chip wire traffic: 2*(W-1)/W of the payload
    wire = ar_bytes * 2 * (W - 1) / W
    t_comm = wire / _ICI_BW
    t_comp = (step_flops / (_V5E_PEAK_BF16 * _ASSUMED_MFU)
              if step_flops else None)
    out = {"all_reduce_payload_bytes": int(ar_bytes),
           "wire_bytes_per_chip": int(wire),
           "assumed_ici_bytes_per_s": _ICI_BW,
           "assumed_peak_flops_bf16": _V5E_PEAK_BF16,
           "assumed_mfu": _ASSUMED_MFU,
           "t_comm_s": round(t_comm, 6)}
    if t_comp:
        out["t_compute_s"] = round(t_comp, 6)
        out["projected_efficiency_no_overlap"] = round(
            t_comp / (t_comp + t_comm), 4)
        out["projected_efficiency_full_overlap"] = round(
            min(1.0, t_comp / max(t_comp, t_comm)), 4)
        out["step_flops"] = step_flops
    return out


_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all",
                "reduce-scatter", "collective-permute")


def _planned_step_collectives(kind, world):
    """Compile ONE planned training step of a tiny model-parallel
    workload and count the collectives GSPMD emitted into its HLO."""
    import numpy as np

    from singa_tpu import opt, tensor
    from singa_tpu.parallel import sharding as shd

    rng = np.random.RandomState(0)
    if kind == "sp":
        # ring attention: flash kernel per hop inside shard_map; the
        # HLO's collective-permute bytes are the MEASURED fwd+bwd ring
        # wire cost (the analytic ici_projection_ring_attention row
        # otherwise assumes ~3x the forward K/V bytes for training)
        from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

        mesh = shd.create_mesh(sp=world)
        plan = shd.ShardingPlan(mesh)
        m = GPT2LMHead(GPT2Config.tiny(dropout=0.0, attn_impl="flash"),
                       plan=plan)
        ids = tensor.from_numpy(
            rng.randint(0, 256, (1, 8 * world)).astype(np.int32))
        labels = tensor.from_numpy(
            rng.randint(0, 256, (1, 8 * world)).astype(np.int32))
    elif kind == "tp":
        from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

        mesh = shd.create_mesh(dp=2, tp=world // 2)
        plan = shd.ShardingPlan(mesh)
        m = GPT2LMHead(GPT2Config.tiny(dropout=0.0), plan=plan)
        ids = tensor.from_numpy(
            rng.randint(0, 256, (4, 16)).astype(np.int32))
        labels = tensor.from_numpy(
            rng.randint(0, 256, (4, 16)).astype(np.int32))
    elif kind == "ep":
        from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

        mesh = shd.create_mesh(dp=2, ep=world // 2)
        plan = shd.ShardingPlan(mesh)
        m = GPT2LMHead(GPT2Config.tiny(dropout=0.0, moe_every=1,
                                       moe_experts=world // 2),
                       plan=plan)
        ids = tensor.from_numpy(
            rng.randint(0, 256, (4, 16)).astype(np.int32))
        labels = tensor.from_numpy(
            rng.randint(0, 256, (4, 16)).astype(np.int32))
    else:  # pp
        from singa_tpu.parallel.pipeline import PipelinedTransformer
        from singa_tpu import autograd, layer, model as model_mod

        mesh = shd.create_mesh(dp=2, pp=world // 2)
        plan = shd.ShardingPlan(mesh)
        pp = world // 2

        class PipeLM(model_mod.Model):
            def __init__(self):
                super().__init__()
                self.embed = layer.Embedding(64, 16)
                self.trunk = PipelinedTransformer(
                    pp, 2, 32, plan=plan, num_microbatches=2 * pp)
                self.head = layer.Linear(64)
                self.loss_fn = layer.SoftMaxCrossEntropy()

            def forward(self, ids):
                return self.head(self.trunk(self.embed(ids)))

            def train_one_batch(self, ids, labels):
                logits = self.forward(ids)
                b, s, v = logits.shape
                loss = self.loss_fn(
                    autograd.reshape(logits, (b * s, v)),
                    autograd.reshape(labels, (b * s,)))
                self.optimizer(loss)
                return logits, loss

        m = PipeLM()
        ids = tensor.from_numpy(
            rng.randint(0, 64, (4 * pp, 8)).astype(np.int32))
        labels = tensor.from_numpy(
            rng.randint(0, 64, (4 * pp, 8)).astype(np.int32))

    m.set_sharding_plan(plan)
    m.set_optimizer(opt.SGD(lr=0.01))
    m.compile([ids], is_train=True, use_graph=True)
    m(ids, labels)
    hlo = _hlo_of(m)
    out = {k: _count_ops(hlo, k) for k in _COLLECTIVES}
    out["collective_bytes_per_step"] = {
        k: int(_collective_bytes(hlo, k)) for k in _COLLECTIVES
        if _count_ops(hlo, k)}
    out["mesh"] = {a: int(s) for a, s in plan.mesh.shape.items()
                   if s > 1}
    return out


# flash-attention kernel times MEASURED on the real v5e chip this round
# (2026-07-30, round 4) at the ring-attention per-hop shape — on-device
# fori_loop with loop-carried dependence, N=20 vs N=1 differencing (the
# tunnel-RTT-proof protocol).  B=1, H=12 heads, S_local=8192, D=64,
# causal, bf16 — i.e. one GPT-2-small attention hop when the global
# sequence W*8192 is sharded over the ('seq',) mesh axis.
_RING_HOP = {
    "B": 1, "H": 12, "S_local": 8192, "D": 64, "dtype": "bf16",
    "t_fwd_s": 3.607e-3,      # flash kernel fwd (causal)
    "t_fwd_bwd_s": 7.161e-3,  # fwd + dq + dkv kernels
}


def _ring_attention_projection(worlds=(8, 16)):
    """Analytic ICI row for ring attention (round-3 verdict item 1a):
    per-hop K/V bytes x (W-1) hops vs the MEASURED per-hop flash kernel
    time, same method as ici_projection_flagship.  Forward rotates K+V
    once per hop; training adds the dK/dV rotations on the backward
    ring (~2x the forward wire), while per-hop compute roughly doubles
    — so forward is the conservative (comm-heaviest) ratio and both are
    reported.  Per-hop compute is constant in W (S_local fixed), so the
    projection holds at any ring size the mesh offers: growing W grows
    the trainable global sequence (W * S_local), not the per-chip load
    — the §5.7 scaling story."""
    h = _RING_HOP
    bytes_el = 2  # bf16 wire
    kv_bytes_hop = 2 * h["B"] * h["H"] * h["S_local"] * h["D"] * bytes_el
    out = {"workload": ("gpt2-small ring attention, per-hop flash "
                        "kernel MEASURED on the real v5e chip "
                        "(on-device loop differencing)"),
           "per_hop_shape": {k: h[k] for k in
                             ("B", "H", "S_local", "D", "dtype")},
           "kv_bytes_per_hop": kv_bytes_hop,
           "t_hop_comm_s": round(kv_bytes_hop / _ICI_BW, 6),
           "t_hop_fwd_s_measured": h["t_fwd_s"],
           "t_hop_fwd_bwd_s_measured": h["t_fwd_bwd_s"],
           "assumed_ici_bytes_per_s": _ICI_BW}
    for w in worlds:
        t_comm = kv_bytes_hop / _ICI_BW          # per fwd hop
        t_comm_train = 4 * t_comm                # HLO-measured factor
        fwd_no = h["t_fwd_s"] / (h["t_fwd_s"] + t_comm)
        fwd_full = min(1.0, h["t_fwd_s"] / max(h["t_fwd_s"], t_comm))
        tr_no = h["t_fwd_bwd_s"] / (h["t_fwd_bwd_s"] + t_comm_train)
        tr_full = min(1.0, h["t_fwd_bwd_s"] / max(h["t_fwd_bwd_s"],
                                                  t_comm_train))
        # CAUSAL rows (round 5): the balanced zigzag layout
        # (parallel/ring_attention.zigzag_ring_self_attention) makes
        # every rank's hop exactly two dense (S_local/2)^2
        # half-attentions = HALF the measured dense hop compute, with
        # identical K/V wire — so causal efficiency is the dense row
        # at t_hop/2.  The contiguous causal layout is NOT this: its
        # last rank pays the full dense hop while rank 0 idles after
        # one, so its wall-clock equals the dense row with half the
        # mesh idle (ring_causal_half_pairs_per_rank quantifies the
        # 4(i+1)-vs-uniform skew).
        cz_fwd = h["t_fwd_s"] / 2
        cz_tr = h["t_fwd_bwd_s"] / 2
        out[f"W{w}"] = {
            "global_seqlen": w * h["S_local"],
            "hops": w - 1,
            "fwd_efficiency_no_overlap": round(fwd_no, 4),
            "fwd_efficiency_full_overlap": round(fwd_full, 4),
            "train_efficiency_no_overlap": round(tr_no, 4),
            "train_efficiency_full_overlap": round(tr_full, 4),
            "causal_zigzag": {
                "t_hop_fwd_s": round(cz_fwd, 6),
                "fwd_efficiency_no_overlap": round(
                    cz_fwd / (cz_fwd + t_comm), 4),
                "fwd_efficiency_full_overlap": round(
                    min(1.0, cz_fwd / max(cz_fwd, t_comm)), 4),
                "train_efficiency_no_overlap": round(
                    cz_tr / (cz_tr + t_comm_train), 4),
                "train_efficiency_full_overlap": round(
                    min(1.0, cz_tr / max(cz_tr, t_comm_train)), 4),
                "per_rank_balance": "uniform (2(W-1)+4 half-pairs/pass)",
            },
        }
    out["causal_note"] = (
        "causal_zigzag rows: analytic halving of the MEASURED dense "
        "per-hop flash time (two (S_local/2)^2 half-pairs per hop), "
        "balanced across ranks by the zigzag stripe layout; equality "
        "and per-rank balance are tested on the virtual mesh "
        "(tests/test_parallel.py::test_zigzag_*)")
    return out


def _tp_decode_collectives(world, n_new=6):
    """Round-5 verdict item 6: compile ONE plan-sharded KV-decode
    generation (the whole prefill+scan executable, exactly what
    ``generate`` runs) on a tp=world mesh and count the collectives
    GSPMD put INSIDE the decode loop body — the per-token wire cost.
    Instructions outside the while-body (prefill's) execute once per
    call and are reported separately."""
    import jax
    import jax.numpy as jnp

    from singa_tpu import tensor
    from singa_tpu.models import gpt2_decode as gd
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.parallel import sharding as shd

    mesh = shd.create_mesh(tp=world)
    plan = shd.ShardingPlan(mesh)
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg, plan=plan)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    params = gd.extract_params(m)
    window = np.zeros((1, cfg.n_positions), np.int32)
    window[0, :8] = np.arange(8) % cfg.vocab_size
    keys = jax.random.split(jax.random.PRNGKey(0), 1)
    compiled = gd.generate_cached_uniform.lower(
        params, jnp.asarray(window), 8, cfg.n_head,
        float(cfg.layer_norm_eps), n_new, cfg.n_positions, True,
        jnp.float32(1.0), keys).compile()
    hlo = compiled.as_text()
    comps = _hlo_computations(hlo)
    # the decode scan lowers to a while; its body computation is the
    # one containing the per-token collectives (largest body with a
    # dynamic-update-slice on the cache works as the identifying
    # heuristic; collectives in ALL while bodies are summed)
    body_names = set()
    import re

    for mt in re.finditer(r"body=%?([\w.\-]+)", hlo):
        body_names.add(mt.group(1))
    per_tok = {k: 0 for k in _COLLECTIVES}
    per_tok_bytes = {k: 0 for k in _COLLECTIVES}
    for name in body_names:
        body = comps.get(name, "")
        for k in _COLLECTIVES:
            per_tok[k] += _count_ops(body, k)
            per_tok_bytes[k] += int(_collective_bytes(body, k))
    out = {
        "workload": ("gpt2-tiny (2 blocks) plan-sharded KV decode, "
                     "tp=%d virtual mesh, whole-generation executable"
                     % world),
        "per_token_collectives": {k: v for k, v in per_tok.items() if v},
        "per_token_collective_bytes": {
            k: v for k, v in per_tok_bytes.items() if v},
        "module_total_collectives": {
            k: _count_ops(hlo, k) for k in _COLLECTIVES
            if _count_ops(hlo, k)},
        "note": ("per_token_* counts instructions inside while-loop "
                 "bodies (execute once per emitted token); the module "
                 "totals minus these are prefill collectives, paid "
                 "once per generation"),
    }
    return out


def _tp_decode_projection(worlds=(2, 4, 8)):
    """Analytic tokens/sec-vs-W for TP-sharded KV decode of GPT-2 small
    (same method as ici_projection_flagship: measured 1-chip time +
    exact payload arithmetic + assumed ICI constants).  Decode is
    weight-read-bound, so per-step compute scales ~1/W as TP shards
    the weight reads; the wire cost is Megatron's 2 all-reduces per
    block on the (B, 1, E) activation plus the final logits exchange —
    LATENCY-dominated at decode's tiny payloads, which is why decode
    TP efficiency dies faster than training TP."""
    import json as _json

    try:
        with open(os.path.join(_REPO, "BENCH_BASELINE.json")) as f:
            base = _json.load(f)
        tok_s = float(base["workloads"]["gpt2_decode"])
    except (OSError, KeyError, ValueError):
        return {"error": "no gpt2_decode baseline"}
    B, L, E, V = 8, 12, 768, 50257
    t_step1 = B / tok_s                      # 1-chip per-decode-step s
    lat = 5e-6                               # assumed per-collective s
    out = {"workload": "gpt2-small KV decode b8 bf16 (BENCH row)",
           "t_step_1chip_s_measured": round(t_step1, 6),
           "assumed_ici_bytes_per_s": _ICI_BW,
           "assumed_collective_latency_s": lat,
           "arithmetic": ("per token, matching the MEASURED "
                          "hlo_tp_decode loop-body counts (2L+1 "
                          "all-reduces + 2 all-gathers on the L=2 "
                          "model): 2L block all-reduces of (B,E) bf16 "
                          "activations + 1 head all-reduce, + the "
                          "(B, V/W) logits all-gather and one tiny "
                          "sampling gather; compute scales 1/W "
                          "(weight-read-bound)")}
    for w in worlds:
        ar_wire = B * E * 2 * 2 * (w - 1) / w      # ring AR bytes/chip
        ag_wire = B * V * 2 * (w - 1) / w          # logits all-gather
        t_comm = (2 * L + 1) * (lat + ar_wire / _ICI_BW) \
            + 2 * lat + ag_wire / _ICI_BW
        t_comp = t_step1 / w
        t_tok = t_comp + t_comm                    # serial: no overlap
        out[f"W{w}"] = {
            "t_comm_s": round(t_comm, 7),
            "t_compute_s": round(t_comp, 7),
            "tokens_per_sec": round(B / t_tok, 1),
            "speedup_vs_1chip": round(t_step1 / t_tok, 3),
            "efficiency_vs_ideal": round(t_step1 / w / t_tok, 4),
        }
    out["reading"] = (
        "decode TP helps wall-clock latency until the fixed "
        "per-collective latency (~2L+1 collectives/token) eats the "
        "1/W compute win; the crossover is where "
        "t_comm ~ t_compute. Per-token payloads are KB-scale, so "
        "bandwidth is irrelevant - this is a latency story, unlike "
        "training TP where the same collectives carry (B,S,E) tiles.")
    return out


def _flagship_projection(W):
    """Projected W-chip DistOpt scaling efficiency for the flagship
    BENCH workload (ResNet-50, batch 128/chip, bf16 amp): t_comp is the
    REAL v5e chip's measured step time (BENCH_BASELINE.json), the wire
    payload is the exact parameter byte count (dense fp32 grads; the
    bf16 wire mode halves it).  Ring all-reduce traffic 2(W-1)/W."""
    from singa_tpu.models.resnet import resnet50
    from singa_tpu import tensor as st_tensor

    m = resnet50(num_classes=1000)
    x = st_tensor.from_numpy(
        np.zeros((1, 3, 224, 224), np.float32))
    m.compile([x], is_train=False, use_graph=False)
    param_bytes = sum(
        int(np.prod(t.shape)) * 4 for t in m.get_params().values())

    try:
        with open(os.path.join(_REPO, "BENCH_BASELINE.json")) as f:
            base = json.load(f)
    except (OSError, json.JSONDecodeError):
        base = {}
    tp = base.get("workloads", {}).get("resnet50") or base.get("value")
    if not tp:
        return {"error": "no measured resnet50 baseline found"}
    batch = base.get("config", {}).get("batch", 128)
    t_comp = batch / float(tp)
    out = {"workload": "resnet50 bf16 b128 (BENCH flagship)",
           "t_compute_s_measured_real_chip": round(t_comp, 6),
           "param_bytes_fp32": param_bytes,
           "assumed_ici_bytes_per_s": _ICI_BW}
    for w in sorted({W, 16, 64}):
        wire = param_bytes * 2 * (w - 1) / w
        t_comm = wire / _ICI_BW
        out[f"projected_efficiency_W{w}_fp32wire"] = round(
            t_comp / (t_comp + t_comm), 4)
        out[f"projected_efficiency_W{w}_bf16wire"] = round(
            t_comp / (t_comp + t_comm / 2), 4)
    return out


def _pull_worker_jit(f):
    """Sum the WORKER-side jit-cache censuses over the telemetry op
    (None if any worker's jax build can't count)."""
    total = 0
    for i in range(f.replicas):
        v = f.supervisor(i)._conn.call(
            "telemetry", {"jit": True}, timeout=10.0,
            fault_site="serve.dist.telemetry")["value"].get("jit_cache")
        if v is None:
            return None
        total += v
    return total


def _federation_evidence(f, args, jit_cold, jit_warm):
    """Phase-2 federation measurement over the live 2-process fleet:
    kill the telemetry channel (one lost pull -> typed ``stale``, the
    next pull recovers), take the final federated pull, then write and
    strictly re-parse the three merged artifacts, checking the gate's
    invariants — >=2 host pids + a cross-host flow arrow in the trace,
    dual per-host step-anatomy lanes (cat step.host/step.device under
    every host pid, observe.stepprof on each worker) with a measured
    per-host bubble, ``+Inf`` bucket == ``_count`` for every federated
    histogram ladder, why_slow latency fractions summing to 1 with the
    exact ``ship`` phase, and zero warm recompiles with federation
    (profiler included) on."""
    from singa_tpu.observe import health_report
    from singa_tpu.resilience import FailOnce, faults

    # telemetry-channel death: serving untouched, typed degradation
    faults.inject("serve.dist.telemetry", FailOnce())
    f._maybe_pull_telemetry(force=True)
    stale_seen = health_report()["serve"]["dist"]["stale_hosts"]
    f._maybe_pull_telemetry(force=True)       # recovery + fresh pull
    ds = health_report()["serve"]["dist"]
    # federation observes, never compiles: the clock probes + pulls
    # above must leave every worker jit cache exactly where the warm
    # repeat left it
    jit_end = _pull_worker_jit(f)

    ws = ds["why_slow"]
    lat = ws["latency_p99_attribution"]
    frac_sum = sum(p["frac"] for p in lat.values())

    # the federated histogram contract, per host series
    fams, inf_ok, pick = 0, True, None
    for _host, hh in f.telemetry.hosts.items():
        if hh.registry is None:
            continue
        for mtr in hh.registry["metrics"]:
            if mtr["kind"] != "histogram":
                continue
            fams += 1
            inf_ok &= (mtr["buckets"][-1][1] == mtr["count"])
            if pick is None and mtr["count"]:
                pick = mtr["name"]
    mh = f.telemetry.merged_histogram(pick) if pick else None

    # artifacts: merged Chrome trace, host-labeled exposition, fleet
    # request log — re-parsed STRICTLY after writing (a NaN/Inf is a
    # write-time error here, not a viewer surprise later)
    tpath = os.path.join(_REPO, args.trace_out)
    ppath = os.path.join(_REPO, args.prom_out)
    rpath = os.path.join(_REPO, args.request_log)
    n_ev = f.telemetry.write_chrome_trace(tpath)
    prom = f.telemetry.prometheus_text()
    with open(ppath, "w") as fh:
        fh.write(prom)
    n_req = f.telemetry.write_request_log(rpath)

    def _no_const(s):
        raise ValueError(f"non-strict JSON constant: {s}")

    with open(tpath) as fh:
        doc = json.load(fh, parse_constant=_no_const)
    with open(rpath) as fh:
        for line in fh:
            json.loads(line, parse_constant=_no_const)
    pids = sorted({e["pid"] for e in doc["traceEvents"]})
    host_pids = [p for p in pids if p >= 10]
    flows = doc["otherData"]["cross_host_flows"]
    # per-host step-anatomy lanes (observe.stepprof on every worker,
    # enabled by the federate init flags): the workers' cat=step.host/
    # step.device records ship over the trace channel and land as two
    # lanes inside each host's pid in the merged document — the
    # host-vs-device decomposition is per-HOST evidence, not just a
    # single-process number
    step_lane_pids = sorted({e["pid"] for e in doc["traceEvents"]
                             if e.get("cat") == "step.host"
                             and e["pid"] >= 10})
    dev_lane_pids = sorted({e["pid"] for e in doc["traceEvents"]
                            if e.get("cat") == "step.device"
                            and e["pid"] >= 10})
    host_anatomy = {h: d.get("step_anatomy")
                    for h, d in ds["hosts"].items()}

    fed = {
        "hosts": sorted(ds["hosts"]),
        "worker_pids": {h: d["pid"] for h, d in ds["hosts"].items()},
        "clock": {h: d["clock"] for h, d in ds["hosts"].items()},
        "pulls": {h: d["pulls"] for h, d in ds["hosts"].items()},
        "stale_seen": stale_seen,
        "stale_after_recovery": ds["stale_hosts"],
        "why_slow": {
            "latency_frac_sum": round(frac_sum, 6),
            "ttft_phases": sorted(ws["ttft_p99_attribution"]),
            "straggler_host": ws["straggler_host"],
        },
        "trace": {"events": n_ev, "pids": pids,
                  "host_pids": host_pids,
                  "cross_host_flows": flows,
                  "step_anatomy_host_pids": step_lane_pids,
                  "step_device_host_pids": dev_lane_pids},
        # per-host mean device-bubble from the shipped serve.step.*
        # registries (federate.section()): which HOST's engine is
        # host-bound — the fleet-scale ROADMAP item-5 baseline
        "step_anatomy": host_anatomy,
        "prometheus": {
            "bytes": len(prom),
            "host_labeled_series": prom.count('host="'),
            "histogram_families": fams,
            "inf_bucket_equals_count": bool(inf_ok),
        },
        "fleet_histogram": (None if mh is None else {
            "name": mh["name"], "count": mh["count"],
            "per_host_counts": mh["per_host_counts"],
            "p50": mh["p50"], "p99": mh["p99"]}),
        "request_log_entries": n_req,
        "jit_cache_before_warm_repeat": jit_cold,
        "jit_cache_after_warm_repeat": jit_warm,
        "recompiles_warm": (None if jit_cold is None
                            or jit_warm is None
                            else jit_warm - jit_cold),
        "recompiles_federation": (
            None if jit_warm is None or jit_end is None
            else jit_end - jit_warm),
        "artifacts": {"trace": args.trace_out,
                      "prom": args.prom_out,
                      "request_log": args.request_log},
    }
    assert stale_seen == ["w0"], stale_seen
    assert ds["stale_hosts"] == [], ds
    assert abs(frac_sum - 1.0) < 1e-6, lat
    assert "ship" in ws["ttft_p99_attribution"], ws
    assert len(host_pids) >= 2, pids
    assert flows >= 1, doc["otherData"]
    # dual step-anatomy lanes must appear under EVERY host pid, and
    # every host's shipped registry must carry a measured bubble —
    # the dist gate's step-anatomy acceptance
    assert len(step_lane_pids) >= 2, (step_lane_pids, pids)
    assert step_lane_pids == dev_lane_pids, (step_lane_pids,
                                             dev_lane_pids)
    assert all(a is not None and a["steps"] > 0
               and a["bubble_frac"] > 0.0
               for a in host_anatomy.values()), host_anatomy
    assert fams > 0 and inf_ok, (fams, inf_ok)
    assert fed["recompiles_warm"] in (0, None), fed
    assert fed["recompiles_federation"] in (0, None), fed
    assert n_req >= 2 and n_ev > 0, (n_req, n_ev)
    return fed


def _fleet_smoke(args):
    """``--fleet``: the multi-host serving smoke (the dist round) —
    a 2-PROCESS local DistFleet on CPU proving the wire is invisible:
    (1) byte parity with the in-process ServeFleet through the
    unmodified router, (2) one streamed cross-host KV ship with the
    warm repeat's TTFT beating the cold prefill, (3) one worker kill
    with every in-flight request requeued to parity.  Bounded-time:
    this is the tier-1 CI gate next to soak/chaos, not a benchmark —
    wall time rides the JSON so the gate's budget is visible.

    Since the federation round the smoke also proves the fleet can be
    SEEN across the process boundary: phase 2 runs with the request
    ledger + tracing federated over the wire, writes the merged
    2-process Chrome trace (one pid per host, a cross-host flow arrow
    on the KV ship), the host-labeled Prometheus exposition, and the
    fleet-wide request log (``--trace-out`` / ``--prom-out`` /
    ``--request-log``), kills the telemetry channel mid-run to show
    the typed ``stale`` degradation + recovery, and pins the worker
    jit caches across the warm repeat (federation observes, never
    recompiles)."""
    import jax

    from singa_tpu import observe, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe import health_report
    from singa_tpu.resilience import FailOnce, faults
    from singa_tpu.serve import (DistFleet, GenerationRequest,
                                 PagedConfig, PrefixCacheConfig,
                                 ServeFleet, gpt2_spec)

    t_wall = time.time()
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    spec = gpt2_spec(m)
    result = {"bench": "dist_fleet_smoke",
              "schema": "singa_tpu.dist/1",
              "backend": jax.devices()[0].platform,
              "spawn": "process", "replicas": 2}

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 256, rng.randint(4, 9)).astype(np.int32)
               for _ in range(6)]

    def run(fleet, plist, prefix="q"):
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=5, request_id=f"{prefix}{i}"))
            for i, p in enumerate(plist)]
        fleet.run_until_complete(max_steps=800)
        return [[int(t) for t in h.result().tokens] for h in hs]

    def leaks(fleet):
        total = 0
        for i in range(fleet.replicas):
            eng = fleet.supervisor(i).engine
            if eng._closed or eng.paged_arena is None:
                continue
            total += (eng.paged_arena.blocks_used
                      - eng.prefix_cache.cached_blocks)
        return total

    leaked = 0

    # 1. parity across the process boundary ---------------------------
    # (the in-process reference runs UNOBSERVED so the ledger and the
    # merged artifacts below carry only cross-process traffic)
    with ServeFleet(m, replicas=2, max_slots=2) as f:
        want = run(f, prompts)

    observe.clear()
    observe.enable()
    led = observe.requests.enable(capacity=4096)
    faults.clear()

    with DistFleet(spec, replicas=2, spawn="process",
                   max_slots=2) as f:
        got = run(f, prompts)
        pids = [f.supervisor(i).pid for i in range(2)]
        snap = f.snapshot()
    result["parity"] = {
        "requests": len(prompts),
        "byte_identical": got == want,
        "worker_pids": pids,
        "worker_pids_distinct": all(p and p != os.getpid()
                                    for p in pids),
        "rpcs": snap["dist"]["rpcs"],
        "rpc_errors": snap["dist"]["rpc_errors"],
    }
    assert result["parity"]["byte_identical"], "wire parity broken"
    assert result["parity"]["worker_pids_distinct"], pids

    # 2. one streamed ship + warm-vs-cold cross-host TTFT --------------
    doc = rng.randint(0, 256, 96).astype(np.int32)
    kw = dict(roles=("prefill", "decode"), max_slots=2,
              paged=PagedConfig(block_size=8, num_blocks=64),
              prefix_cache=PrefixCacheConfig(block_size=8))
    with DistFleet(spec, replicas=2, spawn="process", **kw) as f:
        h1 = f.submit(GenerationRequest(doc, max_new_tokens=4,
                                        request_id="cold"))
        f.run_until_complete(max_steps=800)
        cold = h1.result()
        h2 = f.submit(GenerationRequest(doc, max_new_tokens=4,
                                        request_id="warm"))
        f.run_until_complete(max_steps=800)
        warm = h2.result()
        # steady-state recompile pin: the FIRST warm repeat may compile
        # the warm-admission executables once; the second identical
        # repeat must compile NOTHING (worker-side census over the
        # telemetry op — this is the cross-process bench_serve pin)
        jit_cold = _pull_worker_jit(f)
        h3 = f.submit(GenerationRequest(doc, max_new_tokens=4,
                                        request_id="warm2"))
        f.run_until_complete(max_steps=800)
        warm2 = h3.result()
        jit_warm = _pull_worker_jit(f)
        assert [int(t) for t in warm2.tokens] \
            == [int(t) for t in warm.tokens]
        snap = f.snapshot()
        leaked += leaks(f)
        result["federation"] = _federation_evidence(f, args, jit_cold,
                                                    jit_warm)
    result["ship"] = {
        "doc_tokens": int(len(doc)),
        "ships": snap["ships"],
        "ship_fallbacks": snap["ship_fallbacks"],
        "frames": snap["dist"]["frames"],
        "frame_bytes": snap["dist"]["frame_bytes"],
        "ship_s_mean": snap["dist"]["ship_s_mean"],
        "cold_ttft_s": round(cold.ttft, 4),
        "warm_ttft_s": round(warm.ttft, 4),
        "warm_beats_cold": bool(warm.ttft < cold.ttft),
        "tokens_identical": ([int(t) for t in warm.tokens]
                             == [int(t) for t in cold.tokens]),
    }
    assert snap["ships"] >= 1 and snap["dist"]["frames"] > 0, snap
    assert result["ship"]["tokens_identical"]
    assert result["ship"]["warm_beats_cold"], \
        (cold.ttft, warm.ttft)

    # 3. one kill: a worker severed mid-flight -------------------------
    with DistFleet(spec, replicas=2, spawn="process",
                   max_slots=2) as f:
        hs = [f.submit(GenerationRequest(
            p, max_new_tokens=5, request_id=f"k{i}"))
            for i, p in enumerate(prompts[:4])]
        f.step()
        f.kill_worker(0)
        f.run_until_complete(max_steps=800)
        wedged = sum(0 if h.done() else 1 for h in hs)
        got_k = [[int(t) for t in h.result().tokens]
                 for h in hs if h.done()]
        snap = f.snapshot()
        healthy = f.healthy_replicas
    # the kill is OBSERVABLE: every peer-loss lands in the controller
    # ledger as a typed reject hop (requeue continuity keeps the same
    # request id through to its final parity-checked completion)
    peer_lost = sum(
        1 for e in led.entries() for h in e["hops"]
        if (h.get("reject") or {}).get("reason") == "peer_lost")
    result["kill"] = {
        "requests": 4,
        "wedged_or_lost": wedged,
        "completed_with_parity": sum(
            g == w for g, w in zip(got_k, want[:4])),
        "failovers": snap["failovers"],
        "requeues": snap["requeues"],
        "replicas_healthy_after": healthy,
        "peer_lost_hops_recorded": peer_lost,
    }
    assert wedged == 0, f"{wedged} requests wedged after kill"
    assert result["kill"]["completed_with_parity"] == 4
    assert snap["failovers"] >= 1 and healthy == 1
    assert peer_lost >= 1, "kill left no typed reject in the ledger"

    result["blocks_leaked"] = leaked
    assert leaked == 0, f"{leaked} blocks leaked"
    result["wall_s"] = round(time.time() - t_wall, 2)
    result["passed"] = True

    out = args.out if args.out != "SCALING.json" \
        else "MULTICHIP_r06.json"
    with open(os.path.join(_REPO, out), "w") as fh:
        json.dump(result, fh, indent=1)
        fh.write("\n")
    print(json.dumps(result))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--batch-per-chip", type=int, default=16)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--model", default="cnn",
                    choices=["cnn", "resnet18"])
    ap.add_argument("--out", default="SCALING.json")
    ap.add_argument("--fleet", action="store_true",
                    help="multi-host serving smoke: 2-process "
                         "DistFleet parity + one streamed ship + one "
                         "kill (writes MULTICHIP_r06.json by default)")
    ap.add_argument("--trace-out", default="MULTICHIP_trace.json",
                    help="--fleet: merged 2-process Chrome trace "
                         "(one pid per host, cross-host flow arrows)")
    ap.add_argument("--prom-out", default="MULTICHIP_metrics.prom",
                    help="--fleet: federated Prometheus exposition "
                         "(every worker series host= labeled)")
    ap.add_argument("--request-log",
                    default="MULTICHIP_requests.jsonl",
                    help="--fleet: fleet-wide merged request log "
                         "(sealed ledger entries, JSONL)")
    args = ap.parse_args()

    if args.fleet:
        return _fleet_smoke(args)

    _provision_or_reexec(args.world)

    import jax

    from singa_tpu.utils import metrics

    backend = jax.devices()[0].platform
    W = args.world
    result = {"world": W, "batch_per_chip": args.batch_per_chip,
              "model": args.model, "backend": backend,
              "backend_note": ("virtual CPU mesh: validates harness + "
                               "sharding, not ICI bandwidth"
                               if backend == "cpu" else
                               "real accelerator mesh")}

    # 1. scaling efficiency ------------------------------------------------
    m1, x1, y1, b1 = _build(W, args.batch_per_chip, args.model, dist=False)
    t1 = _time_steps(m1, x1, y1, args.iters)
    tp1 = b1 / t1
    mW, xW, yW, bW = _build(W, args.batch_per_chip, args.model, dist=True)
    tW = _time_steps(mW, xW, yW, args.iters)
    tpW = bW / tW
    eff = metrics.scaling_efficiency(tpW, tp1, W)
    result["throughput_1chip"] = round(tp1, 2)
    result["throughput_Wchip"] = round(tpW, 2)
    result["scaling_efficiency"] = round(eff, 4)
    if backend == "cpu":
        result["scaling_efficiency_note"] = (
            "measured on the VIRTUAL CPU MESH with a toy CNN — "
            "validates the harness, says nothing about ICI; quote "
            "ici_projection_flagship for the hardware story")

    # 2. dense vs sparse top-K crossover ----------------------------------
    dense_t = _time_steps(mW, xW, yW, args.iters, dist_option="plain")
    sweeps = {"dense": round(dense_t * 1e3, 3)}
    # wire bytes per step from the HLO: the backend-independent half of
    # the crossover story (CPU-mesh timings say nothing about ICI; the
    # collective payload bytes transfer to any backend)
    wire = {"dense": sum(_collective_bytes(_hlo_of(mW), op)
                         for op in _COLLECTIVES)}
    for k in (0.005, 0.01, 0.05):
        ms, xs, ys, _ = _build(W, args.batch_per_chip, args.model, dist=True)
        t = _time_steps(ms, xs, ys, args.iters,
                        dist_option="sparseTopK", spars=k)
        sweeps[f"topK_{k:g}"] = round(t * 1e3, 3)
        wire[f"topK_{k:g}"] = sum(_collective_bytes(_hlo_of(ms), op)
                                  for op in _COLLECTIVES)
    best = min(sweeps, key=sweeps.get)
    result["per_step_ms"] = sweeps
    result["collective_bytes_per_step"] = wire
    result["sparse_crossover_winner"] = best
    result["sparse_crossover_note"] = (
        "winner timed on this backend only; collective_bytes_per_step "
        "is the backend-independent wire cost")

    # 3. partial-update conditional-collective proof ----------------------
    mp, xp, yp, _ = _build(W, args.batch_per_chip, args.model, dist=True)
    _time_steps(mp, xp, yp, 1, dist_option="partialUpdate")
    hlo_partial = _conditional_allreduce_stats(_hlo_of(mp))
    hlo_dense = _conditional_allreduce_stats(_hlo_of(mW))
    result["hlo_partial_update"] = hlo_partial
    result["hlo_dense"] = hlo_dense
    # the 1/W wire claim is proven only if the all-reduces actually sit
    # inside conditional branch computations (not merely "a conditional
    # exists" — round-2 verdict)
    result["partial_update_conditional"] = (
        hlo_partial["conditional_ops"] > 0
        and hlo_partial["all_reduce_in_cond_branches"] > 0)

    # 3b. analytic ICI bridge for THIS TOY HARNESS (tiny CNN whose step
    # is microseconds of compute): the method demo, renamed + annotated
    # so its 10% efficiency can't be quoted as a hardware projection
    # (round-3 verdict, weak #4) — ici_projection_flagship below is the
    # quotable number
    toy = _ici_projection(_hlo_of(mW), _step_flops(m1), W)
    toy["note"] = ("TOY-SCALE ILLUSTRATION of the projection method on "
                   "this harness's microsecond-compute CNN — its low "
                   "efficiency reflects the toy model's size, not the "
                   "framework; quote ici_projection_flagship / "
                   "ici_projection_ring_attention instead")
    result["ici_projection_toy_harness"] = toy

    # 3c. flagship projection: the BENCH workload (ResNet-50, b128)
    # with the REAL-chip measured step time as t_comp and exact param
    # bytes as the ring all-reduce payload — this, not the tiny-CNN row
    # above, is the analytic bridge to the >=90% north star
    result["ici_projection_flagship"] = _flagship_projection(W)

    # 3d. ring-attention projection (round-3 verdict item 1a): measured
    # per-hop flash kernel time vs per-hop K/V wire bytes
    result["ici_projection_ring_attention"] = _ring_attention_projection()
    result["ici_projection_tp_decode"] = _tp_decode_projection()

    # 4. model-parallel collective evidence (GSPMD plan paths) ------------
    # What the partitioner actually emits for tp / ep / pp on this mesh —
    # the Megatron claim is all-reduces proportional to blocks (2 fwd +
    # backward's mirror), MoE dispatch should show all-to-all (or the
    # partitioner's chosen equivalent), and the pipeline must show
    # collective-permute (the ppermute ring hops).
    if W >= 4:
        result["hlo_tensor_parallel"] = _planned_step_collectives("tp", W)
        result["hlo_tp_decode"] = _tp_decode_collectives(min(4, W))
        result["hlo_moe"] = _planned_step_collectives("ep", W)
        result["hlo_pipeline"] = _planned_step_collectives("pp", W)
        ring = _planned_step_collectives("sp", W)
        ring["note"] = (
            "collective_bytes_per_step sums the LOOP-BODY instruction "
            "bytes once; each executes per ring hop, so per-step wire "
            "= bytes x W. The 8 collective-permutes = fwd k/v + bwd "
            "k/v re-rotation + dk/dv cotangents + saved-carry pair: "
            "4x the forward K/V bytes, the factor "
            "ici_projection_ring_attention's train rows use.")
        result["hlo_ring_attention"] = ring

    with open(os.path.join(_REPO, args.out), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
