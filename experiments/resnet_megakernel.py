"""PERF.md §5 lever #2, measured (round-3 verdict item 10): can a
Pallas residual-block megakernel cut ResNet-50's stage-2 inter-op
activation traffic enough to matter end to end?

The experiment: ONE conv2_x bottleneck block (1x1 256→64 · BN · ReLU ·
3x3 64→64 · BN · ReLU · 1x1 64→256 · BN · +skip · ReLU) at the bench
shape (B=128, 56×56, bf16), FORWARD path, BN folded to scale/bias (the
fold is exact for inference and an upper bound on the training win —
training BN needs cross-batch stats the megakernel would have to
round-trip anyway).

* ``xla_chain``  — the same math as lax ops, jitted: XLA fuses the
  BN/ReLU chains into the convs but writes y1 (56·56·64) and y2
  between them.
* ``megakernel`` — one Pallas kernel, grid over images, channels-last:
  the whole 56×56 image + all three weights live in VMEM; the 3x3 is
  nine shifted (3136,64)@(64,64) GEMMs; y1/y2 never touch HBM.

Run on the real chip:  python experiments/resnet_megakernel.py
Appends nothing; PERF.md §6 records the measured outcome.
"""

import functools
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, HW, C, CM = 128, 56, 256, 64  # bottleneck: C -> CM -> CM -> C


def _interpret():
    return jax.default_backend() == "cpu"


def megakernel_block(x, w1, s1, b1, w2, s2, b2, w3, s3, b3):
    """x: (B, HW, HW, C) bf16 channels-last.  One grid step per image;
    y1/y2 stay in VMEM scratch."""

    def kernel(x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, b2_ref,
               w3_ref, s3_ref, b3_ref, o_ref, y1p_ref, y2_ref):
        xb = x_ref[0]                              # (HW, HW, C)
        xf = xb.reshape(HW * HW, C)
        y1 = jnp.maximum(
            jnp.dot(xf, w1_ref[:], preferred_element_type=jnp.float32)
            * s1_ref[:] + b1_ref[:], 0.0)          # (HW*HW, CM) f32
        # write y1 into the CENTER of a zero-padded scratch so the 3x3
        # can read nine statically-shifted views of the ref
        y1p_ref[:] = jnp.zeros_like(y1p_ref)
        y1p_ref[1:HW + 1, 1:HW + 1, :] = \
            y1.astype(jnp.bfloat16).reshape(HW, HW, CM)

        acc = jnp.zeros((HW * HW, CM), jnp.float32)
        for di in range(3):
            for dj in range(3):
                patch = y1p_ref[di:di + HW, dj:dj + HW, :] \
                    .reshape(HW * HW, CM)
                acc = acc + jnp.dot(
                    patch, w2_ref[di, dj],
                    preferred_element_type=jnp.float32)
        y2 = jnp.maximum(acc * s2_ref[:] + b2_ref[:], 0.0)
        y2_ref[:] = y2.astype(jnp.bfloat16)

        # final 1x1 + skip in row chunks: a full (HW², C) f32
        # intermediate alone is 3.2MB and blows the 16MB scoped-VMEM
        # stack together with the stages above
        rows = HW // 4
        for ci in range(4):
            y2c = y2_ref[ci * rows * HW:(ci + 1) * rows * HW, :]
            y3c = jnp.dot(y2c, w3_ref[:],
                          preferred_element_type=jnp.float32) \
                * s3_ref[:] + b3_ref[:]
            xc = x_ref[0, ci * rows:(ci + 1) * rows].reshape(
                rows * HW, C)
            o_ref[0, ci * rows:(ci + 1) * rows] = jnp.maximum(
                y3c + xc.astype(jnp.float32), 0.0
            ).astype(o_ref.dtype).reshape(rows, HW, C)

    vmem = pltpu.VMEM
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, HW, HW, C), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=vmem),  # w1
            pl.BlockSpec(memory_space=vmem),  # s1
            pl.BlockSpec(memory_space=vmem),  # b1
            pl.BlockSpec(memory_space=vmem),  # w2
            pl.BlockSpec(memory_space=vmem),  # s2
            pl.BlockSpec(memory_space=vmem),  # b2
            pl.BlockSpec(memory_space=vmem),  # w3
            pl.BlockSpec(memory_space=vmem),  # s3
            pl.BlockSpec(memory_space=vmem),  # b3
        ],
        out_specs=pl.BlockSpec((1, HW, HW, C), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, HW, HW, C), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((HW + 2, HW + 2, CM), jnp.bfloat16),
            pltpu.VMEM((HW * HW, CM), jnp.bfloat16),
        ],
        interpret=_interpret(),
    )(x, w1, s1, b1, w2, s2, b2, w3, s3, b3)


def xla_chain(x, w1, s1, b1, w2, s2, b2, w3, s3, b3):
    """Same math through lax ops (NHWC) — what the framework's XLA
    pipeline does, minus the batch-stats work of real training BN."""
    dn = jax.lax.conv_dimension_numbers(
        x.shape, (1, 1, C, CM), ("NHWC", "HWIO", "NHWC"))
    y1 = jax.lax.conv_general_dilated(
        x, w1.reshape(1, 1, C, CM), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=jnp.float32)
    y1 = jnp.maximum(y1 * s1 + b1, 0.0).astype(jnp.bfloat16)
    dn2 = jax.lax.conv_dimension_numbers(
        y1.shape, (3, 3, CM, CM), ("NHWC", "HWIO", "NHWC"))
    y2 = jax.lax.conv_general_dilated(
        y1, w2, (1, 1), "SAME", dimension_numbers=dn2,
        preferred_element_type=jnp.float32)
    y2 = jnp.maximum(y2 * s2 + b2, 0.0).astype(jnp.bfloat16)
    dn3 = jax.lax.conv_dimension_numbers(
        y2.shape, (1, 1, CM, C), ("NHWC", "HWIO", "NHWC"))
    y3 = jax.lax.conv_general_dilated(
        y2, w3.reshape(1, 1, CM, C), (1, 1), "SAME",
        dimension_numbers=dn3, preferred_element_type=jnp.float32)
    y3 = y3 * s3 + b3
    return jnp.maximum(y3 + x.astype(jnp.float32), 0.0).astype(x.dtype)


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, HW, HW, C).astype(np.float32) * 0.5
                    ).astype(jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(C, CM).astype(np.float32) * 0.05
                     ).astype(jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(3, 3, CM, CM).astype(np.float32) * 0.05
                     ).astype(jnp.bfloat16)
    w3 = jnp.asarray(rng.randn(CM, C).astype(np.float32) * 0.05
                     ).astype(jnp.bfloat16)
    s1, b1 = (jnp.ones(CM, jnp.float32), jnp.zeros(CM, jnp.float32))
    s2, b2 = (jnp.ones(CM, jnp.float32), jnp.zeros(CM, jnp.float32))
    s3, b3 = (jnp.ones(C, jnp.float32), jnp.zeros(C, jnp.float32))
    args = (w1, s1, b1, w2, s2, b2, w3, s3, b3)

    # correctness first
    ref = jax.jit(xla_chain)(x, *args)
    got = jax.jit(megakernel_block)(x, *args)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    print(f"max |megakernel - xla_chain| = {err:.4f}")
    assert err < 0.5, "megakernel math diverges"

    def timed(fn, n1=5, n2=50):
        @jax.jit
        def loop(x, n):
            def body(i, x):
                return fn(x, *args)
            return jax.lax.fori_loop(0, n, body, x)

        float(loop(x, n1)[0, 0, 0, 0].astype(jnp.float32))
        ts = []
        for _ in range(3):
            t0 = time.time()
            float(loop(x, n2)[0, 0, 0, 0].astype(jnp.float32))
            tn = time.time() - t0
            t0 = time.time()
            float(loop(x, n1)[0, 0, 0, 0].astype(jnp.float32))
            t1 = time.time() - t0
            ts.append((tn - t1) / (n2 - n1) * 1e3)
        return sorted(ts)[1]

    t_xla = timed(xla_chain)
    t_mega = timed(megakernel_block)
    flops = (2 * B * HW * HW * (C * CM + 9 * CM * CM + CM * C))
    print(f"xla_chain : {t_xla:8.3f} ms  "
          f"({flops / t_xla / 1e9:6.1f} TFLOP/s)")
    print(f"megakernel: {t_mega:8.3f} ms  "
          f"({flops / t_mega / 1e9:6.1f} TFLOP/s)")
    print(f"ratio (xla/mega): {t_xla / t_mega:.3f}x")


if __name__ == "__main__":
    main()
