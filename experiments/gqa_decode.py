"""Measure the GQA decode win: steady-state tokens/sec vs n_kv_head.

GQA shrinks the K/V cache (and its per-token read traffic) by
n_head / n_kv_head while leaving per-token GEMM work almost unchanged,
so on a cache-read-bound decode loop fewer KV heads should mean more
tokens/sec.  Same two-length differencing methodology as
bench.bench_gpt2_decode (cancels prefill + dispatch + sampling warmup);
GPT-2 small geometry, bf16 weights, greedy, the bench decode config
(batch 8, prompt 128, 512 new tokens).

Run on the real chip:  python experiments/gqa_decode.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def measure(n_kv_head, batch=8, prompt_len=128, n_new=512, repeats=3,
            quant_cache=False, ctx=1024, attn_window=None):
    import jax
    import jax.numpy as jnp

    from singa_tpu import device, tensor
    from singa_tpu.models import gpt2_decode
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    cfg = GPT2Config.small(n_positions=ctx, dropout=0.0,
                           attn_impl="fused", n_kv_head=n_kv_head,
                           attn_window=attn_window)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 8), np.int32), dev)],
              is_train=False, use_graph=False)
    params = gpt2_decode.extract_params(m, dtype=jnp.bfloat16)

    rng = np.random.RandomState(0)
    window = np.zeros((batch, ctx), np.int32)
    window[:, :prompt_len] = rng.randint(0, cfg.vocab_size,
                                         (batch, prompt_len))
    ids = jnp.asarray(window)
    keys = jax.random.split(jax.random.PRNGKey(0), batch)

    def run(nn):
        out = gpt2_decode.generate_cached_uniform(
            params, ids, prompt_len, cfg.n_head,
            float(cfg.layer_norm_eps), nn, ctx, True,
            jnp.float32(1.0), keys, quant_cache=quant_cache,
            window=gpt2_decode._norm_window(cfg))
        np.asarray(out)

    def warm(nn, tries=3):
        for i in range(tries):
            try:
                run(nn)
                return
            except Exception as e:  # axon remote_compile mid-body drop
                if "remote_compile" not in str(e) or i == tries - 1:
                    raise
                sys.stderr.write(f"retrying compile: {e}\n")

    def timed(nn):
        ts = []
        for _ in range(repeats):
            t0 = time.time()
            run(nn)
            ts.append(time.time() - t0)
        return sorted(ts)[len(ts) // 2]

    warm(n_new)
    warm(n_new // 2)
    ests = sorted(
        batch * (n_new - n_new // 2) / (timed(n_new) - timed(n_new // 2))
        for _ in range(3))
    d = cfg.n_embd // cfg.n_head
    # bf16 values are 2 bytes; int8 is 1 byte plus a 4-byte f32 scale
    # per (token, head) row of D values
    bytes_per = 1 + 4.0 / d if quant_cache else 2
    span = ctx if attn_window is None else min(attn_window, ctx)
    cache_mib = (2 * cfg.n_layer * batch * cfg.n_kv_head * span
                 * d * bytes_per) / 2**20
    return ests[1], ests[0], ests[-1], cache_mib


if __name__ == "__main__":
    for n_kv in (12, 4, 2, 1):
        for quant in (False, True):
            med, lo, hi, cache = measure(n_kv, quant_cache=quant)
            tag = "int8" if quant else "bf16"
            print(f"n_kv_head={n_kv:2d} cache={tag}: {med:7.1f} tok/s "
                  f"[{lo:.1f}, {hi:.1f}]  kv_cache={cache:.0f} MiB",
                  flush=True)
    # long-context rows: at ctx=4096 the cache dominates the weight
    # reads (1152 vs ~250 MiB at full heads) — the regime the int8
    # cache targets
    for n_kv in (12, 4):
        for quant in (False, True):
            med, lo, hi, cache = measure(n_kv, quant_cache=quant,
                                         ctx=4096)
            tag = "int8" if quant else "bf16"
            print(f"ctx=4096 n_kv_head={n_kv:2d} cache={tag}: "
                  f"{med:7.1f} tok/s [{lo:.1f}, {hi:.1f}]  "
                  f"kv_cache={cache:.0f} MiB", flush=True)
    # sliding window at long context: the O(W) rolling cache should
    # put ctx=4096 decode back at ~ctx=W cost
    med, lo, hi, cache = measure(12, ctx=4096, attn_window=1024)
    print(f"ctx=4096 window=1024 cache=bf16: {med:7.1f} tok/s "
          f"[{lo:.1f}, {hi:.1f}]  kv_cache={cache:.0f} MiB",
          flush=True)
