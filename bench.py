"""Benchmark harness — prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary metric (BASELINE.json): ResNet-50 train throughput,
samples/sec/chip, measured on the real attached chip with the full
singa_tpu training step (graph mode: forward + backward + SGD update in
one donated jit executable), bf16 mixed precision (amp policy — fp32
master params, bf16 MXU compute).  The same line carries the second
BASELINE workload (BERT-base masked-LM train throughput, S=512) and
model-FLOPs-utilization (MFU) for both, computed from the compiled
step's XLA cost analysis against the chip's bf16 peak.

``vs_baseline``: BASELINE.json.published is empty (no retrievable
reference numbers — see BASELINE.md provenance), so the ratio is
against the round-1 recorded value in BENCH_BASELINE.json (ResNet-50,
fp32, batch 32: 1052.2 samples/s/chip).
"""

import json
import os
import sys
import time

import numpy as np

# bf16 peak matmul throughput per chip, by device_kind substring
_PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v6", 918e12),
]


def _peak_flops():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def _step_flops(m):
    """FLOPs of one compiled training step, from XLA cost analysis."""
    try:
        for _, cost in m._graph_runner.cost_tables():
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            f = c.get("flops")
            if f:
                return float(f)
    except Exception:
        pass
    return None


def _timed_loop(m, x, y, iters):
    # warm: eager iteration + trace/compile + one replay
    m(x, y)
    m(x, y)
    _, loss = m(x, y)
    float(loss.data)  # sync
    t0 = time.time()
    for _ in range(iters):
        _, loss = m(x, y)
    lv = float(loss.data)  # force completion
    dt = time.time() - t0
    assert np.isfinite(lv), f"loss diverged: {lv}"
    return dt


def bench_resnet50(batch=128, hw=224, iters=20, bf16=True):
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.resnet import resnet50

    amp.enable(bf16)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        m = resnet50(num_classes=1000)
        m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))

        rng = np.random.RandomState(0)
        x = tensor.from_numpy(
            rng.randn(batch, 3, hw, hw).astype(np.float32), dev)
        y = tensor.from_numpy(
            rng.randint(0, 1000, (batch,)).astype(np.int32), dev)
        m.compile([x], is_train=True, use_graph=True, sequential=False)
        dt = _timed_loop(m, x, y, iters)
        return batch * iters / dt, _step_flops(m), iters / dt
    finally:
        amp.enable(False)


def bench_bert(batch=16, seqlen=512, iters=10, bf16=True):
    """BERT-base masked-LM training step (the second BASELINE workload)."""
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.bert import BertConfig, BertForMaskedLM

    amp.enable(bf16)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        cfg = BertConfig.base()
        cfg.max_position_embeddings = seqlen
        m = BertForMaskedLM(cfg)
        m.set_optimizer(opt.SGD(lr=1e-4, momentum=0.9))

        rng = np.random.RandomState(0)
        ids = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32),
            dev)
        labels = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size, (batch, seqlen)).astype(np.int32),
            dev)
        m.compile([ids], is_train=True, use_graph=True, sequential=False)
        dt = _timed_loop(m, ids, labels, iters)
        return batch * iters / dt, _step_flops(m), iters / dt
    finally:
        amp.enable(False)


def main():
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    bert_batch = int(os.environ.get("BENCH_BERT_BATCH", "16"))
    bf16 = os.environ.get("BENCH_BF16", "1") != "0"

    resnet_tp, resnet_flops, resnet_sps = bench_resnet50(
        batch=batch, iters=iters, bf16=bf16)

    bert_tp = None
    try:
        bert_tp, bert_flops, bert_sps = bench_bert(
            batch=bert_batch, bf16=bf16)
    except Exception as e:  # record the resnet number even if bert trips
        sys.stderr.write(f"bench_bert failed: {e}\n")
        bert_flops = bert_sps = None

    # MFU is only reported for bf16 runs: the denominator is the chip's
    # bf16 peak, and TPUs execute fp32 matmuls as multi-pass bf16 so an
    # fp32 "peak" denominator would be fiction.
    peak = _peak_flops() if bf16 else None

    def mfu(flops, steps_per_sec):
        if flops and steps_per_sec and peak:
            return round(flops * steps_per_sec / peak, 4)
        return None

    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("value"):
                vs = resnet_tp / float(base["value"])
        except Exception:
            pass

    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(resnet_tp, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 4),
        "bert_train_throughput": round(bert_tp, 2) if bert_tp else None,
        "resnet50_mfu": mfu(resnet_flops, resnet_sps),
        "bert_mfu": mfu(bert_flops, bert_sps),
        "mfu_denominator": "bf16_peak" if peak else None,
        "bf16": bf16,
        "batch": batch,
        "bert_batch": bert_batch,
        "seqlen": 512,
    }))


if __name__ == "__main__":
    main()
