"""Benchmark harness — prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Workloads (BASELINE.json configs):
  * ResNet-50 train throughput (primary metric), samples/sec/chip,
    bf16 amp, batch 128, graph mode (one donated jit executable).
  * BERT-base masked-LM train, S=512, batch 16 (config #4-ish).
  * MLP (config #1) and char-RNN LSTM (config #3) functional-parity
    workloads (lax.scan LSTM cell — the Pallas fused cell was deleted
    in round 4 after losing/tying at every measurable shape).

Timing protocol: each workload warms (eager + compile + one replay +
sync), then runs ``repeats`` timed windows of ``iters`` steps; the
reported value is the MEDIAN window (min/max recorded for variance).
Device sync (`float(loss)`) happens before the timer starts and at
each window boundary.

``vs_baseline``: per-workload ratios against BENCH_BASELINE.json,
which records the SAME-CONFIG (bf16/b128) numbers from round 2 — a
same-config regression now drops the ratio below 1.0 (round-2 verdict
fix; the old fp32/b32 round-0 value is kept under ``history``).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

# bf16 peak matmul throughput per chip, by device_kind substring
_PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v6", 918e12),
]


def _peak_flops():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def _step_flops(m):
    """FLOPs of one compiled training step, from XLA cost analysis."""
    try:
        for _, cost in m._graph_runner.cost_tables():
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            f = c.get("flops")
            if f:
                return float(f)
    except Exception:
        pass
    return None


def _timed_windows(m, x, y, iters, repeats):
    """Median-of-windows timing: warm fully, then time `repeats`
    windows of `iters` steps each (sync at every boundary)."""
    m(x, y)  # eager warm
    m(x, y)  # trace + compile
    _, loss = m(x, y)
    float(loss.data)  # sync before the first timer starts
    dts = []
    for _ in range(repeats):
        t0 = time.time()
        for _ in range(iters):
            _, loss = m(x, y)
        lv = float(loss.data)  # force completion
        dts.append(time.time() - t0)
    assert np.isfinite(lv), f"loss diverged: {lv}"
    return dts


def _throughput(dts, batch, iters):
    """(median, min, max) samples/sec over the timed windows."""
    tps = sorted(batch * iters / dt for dt in dts)
    return tps[len(tps) // 2], tps[0], tps[-1]


def _timed_windows_multi(m, x, y, n_steps, repeats):
    """Multi-step dispatch timing (repeat mode): each window is ONE
    ``train_n_batches(..., n_steps=K)`` call — K optimizer steps per
    host round-trip, so the tunnel RTT amortizes K× and the
    latency-bound workloads (MLP, char-RNN) report on-device
    throughput instead of dispatch weather (round-5; the reference
    dispatches per iteration)."""
    def last_loss(ret):
        losses = ret[1] if isinstance(ret, (tuple, list)) else ret
        return float(np.asarray(losses.data)[-1])

    ret = m.train_n_batches(x, y, n_steps=n_steps)  # trace + compile
    ret = m.train_n_batches(x, y, n_steps=n_steps)  # warm replay
    lv = last_loss(ret)  # sync
    dts = []
    for _ in range(repeats):
        t0 = time.time()
        ret = m.train_n_batches(x, y, n_steps=n_steps)
        lv = last_loss(ret)  # force completion
        dts.append(time.time() - t0)
    assert np.isfinite(lv), f"loss diverged: {lv}"
    return dts


def bench_resnet50(batch=128, hw=224, iters=20, repeats=3, bf16=True):
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.resnet import resnet50

    amp.enable(bf16)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        m = resnet50(num_classes=1000)
        m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))

        rng = np.random.RandomState(0)
        x = tensor.from_numpy(
            rng.randn(batch, 3, hw, hw).astype(np.float32), dev)
        y = tensor.from_numpy(
            rng.randint(0, 1000, (batch,)).astype(np.int32), dev)
        m.compile([x], is_train=True, use_graph=True, sequential=False)
        dts = _timed_windows(m, x, y, iters, repeats)
        med, lo, hi = _throughput(dts, batch, iters)
        return {"tp": med, "tp_min": lo, "tp_max": hi,
                "flops": _step_flops(m),
                "steps_per_sec": med / batch}
    finally:
        amp.enable(False)


def bench_bert(batch=16, seqlen=512, iters=10, repeats=3, bf16=True):
    """BERT-base masked-LM training step (the second BASELINE workload)."""
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.bert import BertConfig, BertForMaskedLM

    amp.enable(bf16)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        cfg = BertConfig.base()
        cfg.max_position_embeddings = seqlen
        m = BertForMaskedLM(cfg)
        m.set_optimizer(opt.SGD(lr=1e-4, momentum=0.9))

        rng = np.random.RandomState(0)
        ids = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size,
                        (batch, seqlen)).astype(np.int32), dev)
        labels = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size,
                        (batch, seqlen)).astype(np.int32), dev)
        m.compile([ids], is_train=True, use_graph=True, sequential=False)
        dts = _timed_windows(m, ids, labels, iters, repeats)
        med, lo, hi = _throughput(dts, batch, iters)
        return {"tp": med, "tp_min": lo, "tp_max": hi,
                "flops": _step_flops(m),
                "steps_per_sec": med / batch}
    finally:
        amp.enable(False)


def bench_gpt2(batch=8, seqlen=1024, iters=10, repeats=3, bf16=True):
    """GPT-2 small causal-LM training step (beyond-parity transformer
    workload).  attn_impl='auto' resolves to FLASH at S=1024 since the
    round-4 crossover re-sweep (flash +31% over fused here; the full
    long-context regime is swept separately by bench_longctx.py)."""
    from singa_tpu import amp, device, opt, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    amp.enable(bf16)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        cfg = GPT2Config.small(n_positions=seqlen, dropout=0.0)
        m = GPT2LMHead(cfg)
        m.set_optimizer(opt.SGD(lr=1e-4, momentum=0.9))

        rng = np.random.RandomState(0)
        ids = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size,
                        (batch, seqlen)).astype(np.int32), dev)
        labels = tensor.from_numpy(
            rng.randint(0, cfg.vocab_size,
                        (batch, seqlen)).astype(np.int32), dev)
        m.compile([ids], is_train=True, use_graph=True, sequential=False)
        dts = _timed_windows(m, ids, labels, iters, repeats)
        med, lo, hi = _throughput(dts, batch, iters)
        return {"tp": med, "tp_min": lo, "tp_max": hi,
                "flops": _step_flops(m),
                "steps_per_sec": med / batch,
                "tokens_per_sec": med * seqlen}
    finally:
        amp.enable(False)


def _chip_tflops(size=4096, k0=200, k1=1200, repeats=5):
    """Fixed-work chip-health probe (round-5 verdict, weak #2): achieved
    bf16 matmul TFLOP/s from a jitted fori_loop of ``k`` dependent
    (size, size) matmuls, timed at k1 and k0 and DIFFERENCED — the
    dispatch RTT and loop overhead cancel exactly, leaving pure MXU
    time.  A per-iteration tanh keeps activations bounded (and defeats
    loop-invariant hoisting) at O(size²) cost vs the matmul's O(size³).

    Emitted per bench run as ``chip_tflops``: if it is in-band vs
    BENCH_BASELINE.json's ``baseline_chip_tflops``, the chip epoch is
    healthy and a compute-bound workload's vs_baseline < 1 is a real
    code regression, not chip weather."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(size, size) / np.sqrt(size), jnp.bfloat16)

    @partial(jax.jit, static_argnames=("k",))
    def loop(x, k):
        return jax.lax.fori_loop(
            0, k, lambda i, y: jnp.tanh(y @ a), x)

    def timed(k):
        float(loop(a, k=k)[0, 0].astype(jnp.float32))  # compile + warm
        ts = []
        for _ in range(repeats):
            t0 = time.time()
            float(loop(a, k=k)[0, 0].astype(jnp.float32))
            ts.append(time.time() - t0)
        return sorted(ts)[len(ts) // 2]

    dt = timed(k1) - timed(k0)
    if dt <= 0:
        return None
    return round(2 * size ** 3 * (k1 - k0) / dt / 1e12, 1)


def _device_reachable(timeout_s=120):
    """Fail fast when the device never answers (observed round 5: the
    axon tunnel can wedge so hard that even a tiny matmul blocks
    forever — a bench run would then hang until the driver's outer
    timeout with no diagnostic).  The probe runs in a daemon thread so
    a hung backend can't block bench exit.  Returns None when the
    device answered, else a diagnostic string (hang vs init error are
    reported distinctly)."""
    import threading

    ok, err = [], []

    def probe():
        try:
            import jax.numpy as jnp

            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
            ok.append(True)
        except Exception as e:  # init error ≠ hang: diagnose correctly
            err.append(f"{type(e).__name__}: {e}")

    th = threading.Thread(target=probe, daemon=True)
    th.start()
    th.join(timeout_s)
    if ok:
        return None
    if err:
        return f"device probe raised {err[0]}"
    return (f"device unreachable: no response to an 8x8 matmul within "
            f"{timeout_s}s (axon tunnel down?) — rerun when the device "
            f"answers")


def _dispatch_rtt_ms(n=20):
    """Per-session host→device dispatch round-trip (tiny no-op jit +
    scalar readback, median of n).  The axon tunnel makes this vary
    2-10x between sessions, which moves latency-bound workloads
    (charrnn/mlp) while leaving compute-bound ones alone — recording it
    lets readers separate tunnel weather from real regressions
    (round-3 verdict, weak #1)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(f(x))  # compile + first transfer
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(f(x))
        ts.append(time.time() - t0)
    return round(sorted(ts)[n // 2] * 1000, 3)


def bench_gpt2_decode(batch=8, prompt_len=128, n_new=512, repeats=3,
                      bf16=True):
    """KV-cached batched inference (models/gpt2_decode.py): GPT-2 small,
    batch of right-padded prompts, greedy, bf16 weights (decode is
    weight-read-bound; bf16 measured ≈2× over fp32).  The whole
    generation is ONE compiled executable, so the tunnel RTT is paid
    once per call.

    ``decode_tokens_per_sec`` is STEADY-STATE: timed at n_new and
    n_new/2 and differenced, which cancels prefill + dispatch + sampling
    warmup exactly.  The whole differencing procedure repeats ``outer``
    times and the MEDIAN estimate is reported with its [min, max]
    spread (round-5 verdict, weak #1: the old single estimate left a
    0.97 vs_baseline unexplainable).  ``ragged`` adds a second row for
    a mixed-length batch (lengths 0.5×–1.0× prompt_len) decoded through
    the round-5 left-padding fast path — the number users get without
    length-sorting their batches; steady-state differencing keeps it
    comparable to the uniform row (per-token decode work is
    length-independent once the cache is live).  ``first_token_ms`` is the raw
    latency of a prefill+1-token call (RTT included — subtract
    dispatch_rtt_ms for the on-device time)."""
    import jax
    import jax.numpy as jnp

    from singa_tpu import device, tensor
    from singa_tpu.models import gpt2_decode
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    # attn_impl pinned to fused: the layer-stack forward here only
    # exists to deferred-init the params (decode itself is the pure-jnp
    # KV path), and S=1024 auto now resolves to the flash kernel, which
    # the host CppCPU device can't run when the default backend is TPU
    cfg = GPT2Config.small(n_positions=1024, dropout=0.0,
                           attn_impl="fused")
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 8), np.int32), dev)],
              is_train=False, use_graph=False)
    params = gpt2_decode.extract_params(
        m, dtype=jnp.bfloat16 if bf16 else None)

    rng = np.random.RandomState(0)
    ctx = cfg.n_positions
    window = np.zeros((batch, ctx), np.int32)
    window[:, :prompt_len] = rng.randint(0, cfg.vocab_size,
                                         (batch, prompt_len))
    ids = jnp.asarray(window)
    # ragged batch: lengths 0.5×–1.0× prompt_len (mean ~0.78×; less
    # prefill work than the uniform row, same steady-state decode
    # work), LEFT-padded
    r_lens = np.asarray(
        [prompt_len, prompt_len * 3 // 4, prompt_len // 2,
         prompt_len * 7 // 8, prompt_len * 5 // 8,
         prompt_len * 13 // 16, prompt_len * 9 // 16,
         prompt_len * 15 // 16][:batch], np.int32)
    r_lens = np.resize(r_lens, batch)
    max_len = int(r_lens.max())
    r_window = np.zeros((batch, ctx), np.int32)
    for i, ln in enumerate(r_lens):
        r_window[i, max_len - ln:max_len] = rng.randint(
            0, cfg.vocab_size, ln)
    r_ids = jnp.asarray(r_window)
    r_start = jnp.asarray(max_len - r_lens)
    keys = jax.random.split(jax.random.PRNGKey(0), batch)
    args = (cfg.n_head, float(cfg.layer_norm_eps))

    def run(nn):
        # equal-length prompts: the uniform fast path (shared position,
        # batched cache writes) — what generate() auto-selects here
        out = gpt2_decode.generate_cached_uniform(
            params, ids, prompt_len, *args, nn, ctx, True,
            jnp.float32(1.0), keys)
        np.asarray(out)  # sync

    def run_ragged(nn):
        out = gpt2_decode.generate_cached_uniform(
            params, r_ids, max_len, *args, nn, ctx, True,
            jnp.float32(1.0), keys, start=r_start)
        np.asarray(out)

    def timed(fn, nn):
        ts = []
        for _ in range(repeats):
            t0 = time.time()
            fn(nn)
            ts.append(time.time() - t0)
        return sorted(ts)[len(ts) // 2]

    def warm(fn, nn, tries=3):
        # the axon tunnel's remote-compile service occasionally drops
        # the response mid-body on large executables (observed with the
        # round-5 unrolled decode loop); the compile itself is
        # side-effect-free, so retry
        for i in range(tries):
            try:
                fn(nn)
                return
            except Exception as e:
                if "remote_compile" not in str(e) or i == tries - 1:
                    raise
                sys.stderr.write(f"retrying compile after tunnel "
                                 f"error: {e}\n")

    def steady(fn, outer=3):
        warm(fn, n_new)          # compile + warm (full)
        warm(fn, n_new // 2)     # compile + warm (half)
        ests = sorted(
            batch * (n_new - n_new // 2)
            / (timed(fn, n_new) - timed(fn, n_new // 2))
            for _ in range(outer))
        return ests[len(ests) // 2], ests[0], ests[-1]

    med, lo, hi = steady(run)
    r_med, r_lo, r_hi = steady(run_ragged)
    run(1)
    t_first = timed(run, 1)
    return {"tokens_per_sec": med,
            "spread": [round(lo, 1), round(hi, 1)],
            "ragged_tokens_per_sec": r_med,
            "ragged_spread": [round(r_lo, 1), round(r_hi, 1)],
            "ragged_lens": r_lens.tolist(),
            "first_token_ms": round(t_first * 1000, 1),
            "batch": batch, "prompt_len": prompt_len, "n_new": n_new,
            "sampling": "greedy",
            "dtype": "bf16" if bf16 else "fp32",
            "model": "gpt2-small (randomly initialized)"}


def bench_mlp(batch=512, data_size=784, iters=20000, repeats=3):
    """Config #1: MLP (MNIST-shaped), fp32 — functional-parity workload.
    Runs through multi-step dispatch (train_n_batches repeat mode): all
    ``iters`` steps per window compile into ONE lax.scan executable, so
    the reported number is on-device throughput — the single dispatch
    RTT amortizes iters×, instead of one RTT per step."""
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.mlp import MLP

    class LossOnlyMLP(MLP):
        # return only the (K,) loss history from the scan — stacking
        # the (K, B, 10) per-step logits at K=20000 would burn ~400 MB
        # of HBM writes per window for outputs nobody reads
        def train_one_batch(self, x, y):
            _, loss = super().train_one_batch(x, y)
            return loss

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    m = LossOnlyMLP(data_size=data_size, perceptron_size=100,
                    num_classes=10)
    m.set_optimizer(opt.SGD(lr=0.05, momentum=0.9))
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(
        rng.randn(batch, data_size).astype(np.float32), dev)
    y = tensor.from_numpy(
        rng.randint(0, 10, (batch,)).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)
    dts = _timed_windows_multi(m, x, y, iters, repeats)
    med, lo, hi = _throughput(dts, batch, iters)
    return {"tp": med, "tp_min": lo, "tp_max": hi,
            "steps_per_dispatch": iters}


def bench_charrnn(batch=64, seqlen=100, vocab=100, hidden=256, layers=2,
                  iters=1000, repeats=3):
    """Config #3: char-RNN LSTM (lax.scan cell — the Pallas fused cell
    was deleted in round 4 after losing/tying at every measurable
    shape; see ops/rnn.py RNNHandle docstring).  Multi-step dispatch
    (repeat mode): one executable runs all ``iters`` steps, deleting
    the per-step RTT tax.  The bench model returns only the (K,) loss
    history, not (K, B·T, V) stacked logits, to keep HBM flat."""
    from singa_tpu import device, opt, tensor
    from singa_tpu import layer, model, autograd
    from singa_tpu.models.char_rnn import one_hot

    class BenchCharRNN(model.Model):
        def __init__(self):
            super().__init__()
            self.lstm = layer.LSTM(hidden, num_layers=layers,
                                   batch_first=True)
            self.dense = layer.Linear(vocab)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            yv, _ = self.lstm(x)
            return self.dense(autograd.reshape(yv, (-1, hidden)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, autograd.reshape(y, (-1,)))
            self.optimizer(loss)
            return loss

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    m = BenchCharRNN()
    m.set_optimizer(opt.SGD(lr=0.1))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seqlen))
    x = tensor.from_numpy(one_hot(ids, vocab), dev)
    y = tensor.from_numpy(
        np.roll(ids, -1, axis=1).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)
    dts = _timed_windows_multi(m, x, y, iters, repeats)
    med, lo, hi = _throughput(dts, batch, iters)
    return {"tp": med, "tp_min": lo, "tp_max": hi,
            "steps_per_dispatch": iters}


def _load_baseline():
    path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


def main():
    from singa_tpu import observe

    ap = argparse.ArgumentParser(
        description="singa_tpu training benchmark harness")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="trace the whole bench run (compile spans with "
                         "XLA cost tables, train/step dispatches, "
                         "opt/update traces) and write a Chrome "
                         "trace-event JSON there")
    ap.add_argument("--health-out", default=None, metavar="PATH",
                    help="also write observe.health_report() (MFU from "
                         "the XLA cost tables, step-time summaries, "
                         "watchdog state) as JSON")
    cli = ap.parse_args()
    if cli.trace_out:
        observe.enable()
    # active monitoring rides the whole bench (flight recorder + hang
    # watchdog + MFU meter); its overhead is two clock calls and an
    # EWMA update per dispatch — the acceptance bar is < 2% tokens/s
    # and the instrumented dispatches are ≥ milliseconds each.  The
    # timeout is generous: a cold resnet/bert compile on the tunnel
    # legitimately runs minutes with no dispatch heartbeat in between.
    # crash_handler: a bench killed mid-run (uncaught exception,
    # SIGTERM from a CI timeout) leaves a monitor-crash-*.json bundle.
    observe.monitor.start(watchdog_timeout_s=900.0, crash_handler=True)

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    bert_batch = int(os.environ.get("BENCH_BERT_BATCH", "16"))
    bf16 = os.environ.get("BENCH_BF16", "1") != "0"
    skip = set(os.environ.get("BENCH_SKIP", "").split(","))

    probe_err = _device_reachable()
    if probe_err is not None:
        print(json.dumps({
            "metric": "resnet50_train_throughput", "value": 0,
            "unit": "samples/sec/chip", "vs_baseline": 0,
            "error": probe_err}))
        sys.exit(1)
    rtt_ms = _dispatch_rtt_ms()
    try:
        chip_tflops = _chip_tflops()
    except Exception as e:
        sys.stderr.write(f"chip_tflops probe failed: {e}\n")
        chip_tflops = None

    results = {}
    resnet = bench_resnet50(batch=batch, iters=iters, repeats=repeats,
                            bf16=bf16)
    results["resnet50"] = resnet
    for name, fn in (
        ("bert", lambda: bench_bert(batch=bert_batch, repeats=repeats,
                                    bf16=bf16)),
        ("gpt2", lambda: bench_gpt2(repeats=repeats, bf16=bf16)),
        ("mlp", lambda: bench_mlp(repeats=repeats)),
        ("charrnn", lambda: bench_charrnn(repeats=repeats)),
    ):
        if name in skip:
            continue
        try:  # record the resnet number even if a secondary trips
            results[name] = fn()
        except Exception as e:
            sys.stderr.write(f"bench_{name} failed: {e}\n")

    # MFU is only reported for bf16 runs: the denominator is the chip's
    # bf16 peak, and TPUs execute fp32 matmuls as multi-pass bf16 so an
    # fp32 "peak" denominator would be fiction.
    peak = _peak_flops() if bf16 else None

    def mfu(r):
        if r and r.get("flops") and r.get("steps_per_sec") and peak:
            return round(r["flops"] * r["steps_per_sec"] / peak, 4)
        return None

    base = _load_baseline()
    base_workloads = base.get("workloads", {})
    # legacy single-value baseline fallback
    if not base_workloads and base.get("value"):
        base_workloads = {"resnet50": float(base["value"])}
    vs_per = {}
    for name, r in results.items():
        b = base_workloads.get(name)
        if b:
            vs_per[name] = round(r["tp"] / b, 4)

    out = {
        "metric": "resnet50_train_throughput",
        "value": round(resnet["tp"], 2),
        "unit": "samples/sec/chip",
        "vs_baseline": vs_per.get("resnet50", 1.0),
        "vs_baseline_per_workload": vs_per,
        "baseline_config": base.get("config"),
        "repeats": repeats,
        "dispatch_rtt_ms": rtt_ms,
        "chip_tflops": chip_tflops,
        "resnet50_mfu": mfu(resnet),
        "bert_mfu": mfu(results.get("bert")),
        "gpt2_mfu": mfu(results.get("gpt2")),
        "mfu_denominator": "bf16_peak" if peak else None,
        "bf16": bf16,
        "batch": batch,
        "bert_batch": bert_batch,
        "seqlen": 512,
    }
    for name, r in results.items():
        out[f"{name}_train_throughput"] = round(r["tp"], 2)
        out[f"{name}_tp_spread"] = [round(r["tp_min"], 2),
                                    round(r["tp_max"], 2)]
        if "steps_per_dispatch" in r:
            out[f"{name}_steps_per_dispatch"] = r["steps_per_dispatch"]
    # KV-cached inference path (one executable per generation)
    if "decode" not in skip:
        try:
            dec = bench_gpt2_decode(repeats=repeats)
            out["decode_tokens_per_sec"] = round(dec["tokens_per_sec"], 1)
            out["decode_tp_spread"] = dec["spread"]
            out["decode_ragged_tokens_per_sec"] = round(
                dec["ragged_tokens_per_sec"], 1)
            out["decode_ragged_tp_spread"] = dec["ragged_spread"]
            out["decode_first_token_ms"] = dec["first_token_ms"]
            out["decode_config"] = {
                k: dec[k] for k in ("batch", "prompt_len", "n_new",
                                    "sampling", "dtype", "model",
                                    "ragged_lens")}
            b_dec = base_workloads.get("gpt2_decode")
            if b_dec:
                vs_per["gpt2_decode"] = round(
                    dec["tokens_per_sec"] / b_dec, 4)
            b_rag = base_workloads.get("gpt2_decode_ragged")
            if b_rag:
                vs_per["gpt2_decode_ragged"] = round(
                    dec["ragged_tokens_per_sec"] / b_rag, 4)
        except Exception as e:
            sys.stderr.write(f"bench_gpt2_decode failed: {e}\n")
    # long-context headline from the (separately run) LONGCTX sweep:
    # best tokens/s at the longest surviving S (bench_longctx.py
    # re-measures; this just records the standing result)
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "LONGCTX.json")) as f:
            lc = json.load(f)
        alive = [c for c in lc["cells"] if not c.get("failed")]
        top = max(alive, key=lambda c: (c["seqlen"], c["tokens_per_sec"]))
        out["longctx_max_seqlen_1chip"] = top["seqlen"]
        out["longctx_tokens_per_sec"] = top["tokens_per_sec"]
        out["longctx_impl"] = top["impl"]
    except (OSError, KeyError, ValueError):
        pass
    # observe registry: graph cache hit/miss, train.steps, opt.updates —
    # the attribution surface for "where did this bench's time go"
    out["registry"] = observe.registry().snapshot()
    # active-layer summary: MFU/model-flops gauges (XLA step flops ×
    # train.steps rate ÷ chip peak — the per-workload resnet50_mfu
    # above stays the per-workload number; this one is the whole-run
    # rate), per-process step-time summaries, watchdog hang/anomaly
    # state, flight-recorder status.  include_registry=False: the
    # snapshot already rides the top-level `registry` key
    out["health"] = observe.health_report(include_registry=False)
    observe.monitor.stop()
    if cli.trace_out:
        observe.disable()
        out["trace"] = {
            "path": cli.trace_out,
            "trace_events": observe.export.write_chrome_trace(
                cli.trace_out, metadata={"bench": "train"}),
        }
    # strict JSON on stdout/disk: nan (MFU on unknown backends, empty
    # histogram summaries) becomes null — jq-safe BENCH trajectory
    out = observe.export.json_sanitize(out)
    if cli.health_out:
        with open(cli.health_out, "w") as f:
            json.dump(out["health"], f, default=str, allow_nan=False)
    print(json.dumps(out, default=str, allow_nan=False))


if __name__ == "__main__":
    main()
