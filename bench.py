"""Benchmark harness — prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Primary metric (BASELINE.json): ResNet-50 train throughput,
samples/sec/chip, measured on the real attached chip with the full
singa_tpu training step (graph mode: forward + backward + SGD update in
one donated jit executable).

``vs_baseline``: BASELINE.json.published is empty (no retrievable
reference numbers — see BASELINE.md provenance), so the ratio is
against the round-1 recorded value in BENCH_BASELINE.json once it
exists; 1.0 on the first recording.
"""

import json
import os
import sys
import time

import numpy as np


def bench_resnet50(batch=32, hw=224, iters=20, warmup=None):
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.resnet import resnet50

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    m = resnet50(num_classes=1000)
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))

    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(batch, 3, hw, hw).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 1000, (batch,)).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)

    # warm: eager iteration + trace/compile + one replay
    m(x, y)
    m(x, y)
    _, loss = m(x, y)
    float(loss.data)  # sync

    t0 = time.time()
    for _ in range(iters):
        _, loss = m(x, y)
    float(loss.data)  # force completion
    dt = time.time() - t0
    return batch * iters / dt


def main():
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    value = bench_resnet50(batch=batch, iters=iters)

    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "BENCH_BASELINE.json")
    vs = 1.0
    if os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                base = json.load(f)
            if base.get("value"):
                vs = value / float(base["value"])
        except Exception:
            pass
    else:
        try:
            with open(baseline_path, "w") as f:
                json.dump({"metric": "resnet50_train", "value": value,
                           "unit": "samples/sec/chip"}, f)
        except OSError:
            pass

    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
