"""Data feeding helpers (reference: python/singa/data.py, unverified —
batch iterator feeding numpy arrays into training loops).  The heavy
path (BinFile record datasets + threaded native prefetch) lives in
``singa_tpu.io.loader``; this module is the light in-memory iterator the
reference examples use."""

from __future__ import annotations

import numpy as np


class ImageBatchIter:
    """Iterate (x_batch, y_batch) over in-memory arrays with optional
    shuffling and an augmentation callback."""

    def __init__(self, x, y, batch_size, shuffle=True, augment=None, seed=0):
        assert len(x) == len(y)
        self.x = np.asarray(x)
        self.y = np.asarray(y)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.augment = augment
        self.rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self.x) // self.batch_size

    def __iter__(self):
        order = np.arange(len(self.x))
        if self.shuffle:
            self.rng.shuffle(order)
        for i in range(len(self)):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            xb, yb = self.x[idx], self.y[idx]
            if self.augment is not None:
                xb = np.stack([self.augment(v) for v in xb])
            yield xb, yb


def train_test_split(x, y, test_frac=0.2, seed=0):
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(x))
    n_test = int(len(x) * test_frac)
    te, tr = order[:n_test], order[n_test:]
    return (x[tr], y[tr]), (x[te], y[te])
