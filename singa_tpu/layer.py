"""Stateful layer API, shaped after the reference's ``python/singa/layer.py``
(v3 era, ~1.5k LoC, unverified — SURVEY.md §2.2): ``Layer`` base with
parameter creation deferred to the first call (``initialize``), hierarchical
param naming, ``get_params/set_params/get_states/set_states``; concrete
layers ``Linear``, ``Conv2d``, ``BatchNorm2d``, ``Pooling2d`` variants,
``LSTM``, plus op-wrapper layers (``ReLU``, ``Flatten``, losses...).

Conv/BN/Pool/RNN layers call into ``singa_tpu.ops`` (the rebuild of the
reference's ``src/model/operation/*`` cuDNN handle kernels).
"""

from __future__ import annotations

import math

import numpy as np

from . import amp
from . import autograd, initializer, tensor
from .tensor import Tensor


class Layer:
    sep = "."

    def __init__(self):
        self.name = self.__class__.__name__
        self._initialized = False

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, *input):
        """Create params from the first input's shapes (reference: params
        are created on first call, not at construction)."""

    def forward(self, *input):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        if not self._initialized:
            self.initialize(*args, **kwargs)
            self._initialized = True
            self._name_params()
        return self.forward(*args, **kwargs)

    # -- introspection -----------------------------------------------------
    def _sublayers(self):
        out = []
        for attr, val in sorted(self.__dict__.items()):
            if isinstance(val, Layer):
                out.append((attr, val))
            elif isinstance(val, (list, tuple)):
                for i, v in enumerate(val):
                    if isinstance(v, Layer):
                        out.append((f"{attr}{i}", v))
        return out

    def _own_param_attrs(self):
        """Names of attributes holding this layer's own parameter Tensors."""
        return [
            a for a, v in sorted(self.__dict__.items())
            if isinstance(v, Tensor) and v.stores_grad
        ]

    def _own_state_attrs(self):
        """Own non-param persistent state (e.g. BN running stats)."""
        return [
            a for a, v in sorted(self.__dict__.items())
            if isinstance(v, Tensor) and not v.stores_grad
            and getattr(v, "_is_layer_state", False)
        ]

    def _name_params(self):
        for a in self._own_param_attrs() + self._own_state_attrs():
            t = getattr(self, a)
            if t.name is None:
                t.name = f"{self.name}{self.sep}{a}"

    def set_name(self, name):
        self.name = name
        # re-name any already-created param/state tensors to the new
        # hierarchical path (first-call naming may have used the bare
        # class name)
        for a in self._own_param_attrs() + self._own_state_attrs():
            getattr(self, a).name = f"{name}{self.sep}{a}"
        for attr, sub in self._sublayers():
            sub.set_name(f"{name}{self.sep}{attr}")

    # -- params / states ---------------------------------------------------
    def get_params(self) -> dict:
        params = {}
        for a in self._own_param_attrs():
            t = getattr(self, a)
            params[t.name or f"{self.name}{self.sep}{a}"] = t
        for _, sub in self._sublayers():
            params.update(sub.get_params())
        return params

    @staticmethod
    def _load_into(t: Tensor, src):
        """Rebind t's buffer from src, preserving t's device placement.
        Always copies: graph-mode steps donate state buffers, so t must
        not alias the source tensor's buffer."""
        import jax
        import jax.numpy as jnp

        if isinstance(src, Tensor):
            arr = jnp.array(src.data, copy=True)
        else:
            arr = jnp.asarray(np.asarray(src))
        t.data = jax.device_put(arr, t.device.jax_device)
        t.creator = None

    def set_params(self, params: dict):
        for name, t in self.get_params().items():
            if name in params:
                self._load_into(t, params[name])

    def get_states(self) -> dict:
        states = dict(self.get_params())
        for a in self._own_state_attrs():
            t = getattr(self, a)
            states[t.name or f"{self.name}{self.sep}{a}"] = t
        for _, sub in self._sublayers():
            states.update(sub.get_states())
        return states

    def set_states(self, states: dict):
        for name, t in self.get_states().items():
            if name in states:
                self._load_into(t, states[name])

    def register_state(self, t: Tensor):
        """Mark a non-param Tensor as persistent layer state."""
        t._is_layer_state = True
        t.requires_grad = False
        t.stores_grad = False
        return t

    def device_check(self, *inputs):
        devs = {id(x.device) for x in inputs if isinstance(x, Tensor)}
        assert len(devs) <= 1, f"{self.name}: inputs on different devices"


# ---------------------------------------------------------------------------
# Linear
# ---------------------------------------------------------------------------

class Linear(Layer):
    """Reference layer.Linear: y = x W + b, W created as (in, out) on
    first call, xavier-initialized."""

    def __init__(self, out_features, bias=True):
        super().__init__()
        self.out_features = int(out_features)
        self.bias = bool(bias)

    def initialize(self, x):
        in_features = x.shape[-1]
        self.W = Tensor(
            (in_features, self.out_features), device=x.device,
            dtype=amp.param_dtype(x.data.dtype), requires_grad=True, stores_grad=True,
        )
        initializer.xavier(self.W)
        if self.bias:
            self.b = Tensor(
                (self.out_features,), device=x.device, dtype=amp.param_dtype(x.data.dtype),
                requires_grad=True, stores_grad=True,
            )
            self.b.set_value(0.0)

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y


# ---------------------------------------------------------------------------
# op-wrapper layers (stateless; reference v4 exposes these too)
# ---------------------------------------------------------------------------

class ReLU(Layer):
    def forward(self, x):
        return autograd.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return autograd.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, a=0.01):
        super().__init__()
        self.a = a

    def forward(self, x):
        return autograd.leakyrelu(x, self.a)


class Sigmoid(Layer):
    def forward(self, x):
        return autograd.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return autograd.tanh(x)


class Gelu(Layer):
    def forward(self, x):
        return autograd.gelu(x)


class SoftMax(Layer):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return autograd.softmax(x, self.axis)


class Flatten(Layer):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return autograd.flatten(x, self.axis)


class Reshape(Layer):
    def __init__(self, shape):
        super().__init__()
        self.shape = shape

    def forward(self, x):
        return autograd.reshape(x, self.shape)


class Dropout(Layer):
    def __init__(self, ratio=0.5):
        super().__init__()
        self.ratio = ratio

    def forward(self, x):
        return autograd.dropout(x, self.ratio)


class Cat(Layer):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, xs):
        return autograd.cat(xs, self.axis)


class Add(Layer):
    def forward(self, a, b):
        return autograd.add(a, b)


class SoftMaxCrossEntropy(Layer):
    def forward(self, x, t):
        return autograd.softmax_cross_entropy(x, t)


class CrossEntropy(Layer):
    def forward(self, p, t):
        return autograd.cross_entropy(p, t)


class MSELoss(Layer):
    def forward(self, x, t):
        return autograd.mse_loss(x, t)


class BinaryCrossEntropy(Layer):
    def forward(self, p, t):
        return autograd.binary_cross_entropy(p, t)


class LayerNorm(Layer):
    """LayerNormalization over the last axis (BERT convention)."""

    def __init__(self, eps=1e-12):
        super().__init__()
        self.eps = float(eps)

    def initialize(self, x):
        d = x.shape[-1]
        dt = amp.param_dtype(x.data.dtype)
        self.scale = Tensor((d,), device=x.device, dtype=dt,
                            requires_grad=True, stores_grad=True).set_value(1.0)
        self.bias = Tensor((d,), device=x.device, dtype=dt,
                           requires_grad=True, stores_grad=True).set_value(0.0)

    def forward(self, x):
        return autograd.layer_norm(x, self.scale, self.bias, eps=self.eps)


class Embedding(Layer):
    """Token embedding: (B, S) int ids -> (B, S, dim)."""

    def __init__(self, vocab_size, embed_dim, std=0.02):
        super().__init__()
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.std = float(std)

    def initialize(self, ids):
        self.W = Tensor((self.vocab_size, self.embed_dim), device=ids.device,
                        requires_grad=True, stores_grad=True)
        self.W.gaussian(0.0, self.std)

    def forward(self, ids):
        return autograd.embedding(ids, self.W)


# ---------------------------------------------------------------------------
# Conv / BN / Pool / RNN layers — bodies in singa_tpu.ops (added with the
# op kernels; imported lazily so the core has no hard dep ordering)
# ---------------------------------------------------------------------------

class Conv2d(Layer):
    """Reference layer.Conv2d over operation/convolution.cc's ConvHandle
    (unverified).  NCHW layout, like the reference."""

    def __init__(self, nb_kernels, kernel_size, stride=1, padding=0,
                 dilation=1, group=1, bias=True, pad_mode="NOTSET",
                 activation="NOTSET"):
        super().__init__()
        self.nb_kernels = int(nb_kernels)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.dilation = _pair(dilation)
        self.group = int(group)
        self.bias = bool(bias)
        self.pad_mode = pad_mode
        self.activation = activation

    def initialize(self, x):
        in_channels = x.shape[1]
        assert in_channels % self.group == 0
        w_shape = (self.nb_kernels, in_channels // self.group) + self.kernel_size
        self.W = Tensor(w_shape, device=x.device, dtype=amp.param_dtype(x.data.dtype),
                        requires_grad=True, stores_grad=True)
        # reference init: he-style scaled gaussian over receptive field
        std = math.sqrt(2.0 / (w_shape[1] * np.prod(self.kernel_size) + self.nb_kernels))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = Tensor((self.nb_kernels,), device=x.device,
                            dtype=amp.param_dtype(x.data.dtype), requires_grad=True,
                            stores_grad=True)
            self.b.set_value(0.0)

    def forward(self, x):
        from .ops import conv as conv_ops

        y = conv_ops.conv2d(
            x, self.W, self.b if self.bias else None,
            stride=self.stride, padding=self.padding,
            dilation=self.dilation, group=self.group, pad_mode=self.pad_mode,
        )
        if self.activation == "RELU":
            y = autograd.relu(y)
        return y


class ConvTranspose2d(Layer):
    """Transposed convolution (beyond reference parity — upstream has no
    deconv layer; segmentation/decoder models need it).  NCHW layout;
    weight uses the torch/ONNX ConvTranspose convention
    (C_in, C_out/group, kH, kW) so checkpoints and ONNX export line up
    with ops/conv.py conv_transpose2d."""

    def __init__(self, nb_kernels, kernel_size, stride=1, padding=0,
                 dilation=1, group=1, bias=True, output_padding=0):
        super().__init__()
        self.nb_kernels = int(nb_kernels)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.padding = _pair(padding)
        self.dilation = _pair(dilation)
        self.group = int(group)
        self.bias = bool(bias)
        self.output_padding = _pair(output_padding)

    def initialize(self, x):
        in_channels = x.shape[1]
        assert in_channels % self.group == 0
        assert self.nb_kernels % self.group == 0
        w_shape = (in_channels, self.nb_kernels // self.group) \
            + self.kernel_size
        self.W = Tensor(w_shape, device=x.device,
                        dtype=amp.param_dtype(x.data.dtype),
                        requires_grad=True, stores_grad=True)
        std = math.sqrt(2.0 / (w_shape[1] * np.prod(self.kernel_size)
                               + in_channels))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = Tensor((self.nb_kernels,), device=x.device,
                            dtype=amp.param_dtype(x.data.dtype),
                            requires_grad=True, stores_grad=True)
            self.b.set_value(0.0)

    def forward(self, x):
        from .ops import conv as conv_ops

        return conv_ops.conv_transpose2d(
            x, self.W, self.b if self.bias else None,
            stride=self.stride, padding=self.padding,
            dilation=self.dilation, group=self.group,
            output_padding=self.output_padding,
        )


class BatchNorm2d(Layer):
    """Reference layer.BatchNorm2d over operation/batchnorm.cc (cuDNN
    spatial BN, unverified): per-channel affine + running stats."""

    def __init__(self, momentum=0.9, eps=1e-5):
        super().__init__()
        self.momentum = float(momentum)
        self.eps = float(eps)

    def initialize(self, x):
        c = x.shape[1]
        dt = amp.param_dtype(x.data.dtype)
        self.scale = Tensor((c,), device=x.device, dtype=dt,
                            requires_grad=True, stores_grad=True).set_value(1.0)
        self.bias = Tensor((c,), device=x.device, dtype=dt,
                           requires_grad=True, stores_grad=True).set_value(0.0)
        self.running_mean = self.register_state(
            Tensor((c,), device=x.device, dtype=tensor.float32).set_value(0.0))
        self.running_var = self.register_state(
            Tensor((c,), device=x.device, dtype=tensor.float32).set_value(1.0))

    def forward(self, x):
        from .ops import batchnorm as bn_ops

        return bn_ops.batchnorm2d(
            x, self.scale, self.bias, self.running_mean, self.running_var,
            momentum=self.momentum, eps=self.eps,
        )


class Pooling2d(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, is_max=True,
                 pad_mode="NOTSET"):
        super().__init__()
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride) if stride is not None else self.kernel_size
        self.padding = _pair(padding)
        self.is_max = bool(is_max)
        self.pad_mode = pad_mode

    def forward(self, x):
        from .ops import pooling as pool_ops

        return pool_ops.pooling2d(
            x, kernel=self.kernel_size, stride=self.stride,
            padding=self.padding, is_max=self.is_max,
            pad_mode=self.pad_mode,
        )


class MaxPool2d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__(kernel_size, stride, padding, is_max=True, **kw)


class AvgPool2d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0, **kw):
        super().__init__(kernel_size, stride, padding, is_max=False, **kw)


class GlobalAvgPool2d(Layer):
    def forward(self, x):
        return autograd.reduce_mean(x, axes=(2, 3), keepdims=False)


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# RNN layers are defined next to the rnn op kernels and re-exported here.
def __getattr__(name):
    if name in ("LSTM", "GRU", "RNN", "CudnnRNN"):
        from .ops import rnn as rnn_ops

        return getattr(rnn_ops, name)
    if name == "MultiHeadAttention":
        from .ops import attention as attn_ops

        return attn_ops.MultiHeadAttention
    raise AttributeError(name)
