"""``Model`` API, shaped after the reference's ``python/singa/model.py``
(~400 LoC, unverified — SURVEY.md §2.2): ``compile(inputs, is_train,
use_graph, sequential)``, user-overridden ``train_one_batch``,
``set_optimizer``, ``save_states``/``load_states``, train/eval switches.

Graph mode, TPU-native: the reference's buffering graph scheduler
(``src/core/scheduler/scheduler.cc`` — record Exec lambdas on iteration 1,
topo-sort by block deps, replay thereafter) collapses into ``jax.jit``:

  * before iteration 1, an **abstract warm-up** (``jax.eval_shape`` of one
    step) materializes lazily-created optimizer state at zero cost — the
    reference instead executes its first graph iteration eagerly while
    recording, which on this backend would compile every op separately;
  * iteration 1 traces the user's ``train_one_batch`` into one pure
    function over (persistent state, batch) and compiles it with donated
    state buffers — XLA's scheduler then owns op ordering, fusion, memory
    reuse and latency hiding (the jobs of scheduler.cc + cnmem);
  * later iterations replay the cached executable, keyed by input
    shape/dtype like the reference keys its graph on buffered shapes.

"Persistent state" = model params + layer states (BN running stats) +
optimizer state (momentum, step counter) + the device PRNG key (so dropout
advances deterministically inside the compiled step).
"""

from __future__ import annotations

import os
import tempfile
import time as _time
import zipfile
import io as _io

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import autograd, layer, tensor
from .observe import monitor as _monitor
from .observe import trace as _trace
from .observe.registry import registry as _obs_registry
from .resilience import faults as _faults
from .tensor import Tensor

# Default checkpoint file mode (0o666 & ~umask), probed WITHOUT calling
# os.umask(): mutating the process-global umask — even briefly at
# import — would race any other thread creating files (advisor r04).
# Instead, the kernel applies the umask for us to a throwaway O_CREAT
# file, whose stat we read.  Lazy + cached: the probe touches the
# filesystem once per process, at first save.
_CKPT_MODES = {}


def _ckpt_mode(ckpt_dir):
    """Probe in the CHECKPOINT directory itself: it is known writable
    (the save is about to mkstemp there) and carries the ACL defaults
    the checkpoint will actually get — a tempdir probe would fail on
    read-only /tmp sandboxes and could mismatch.  Cached PER
    DIRECTORY, matching that rationale (a second save into a
    directory with different default ACLs re-probes; a benign
    double-probe between concurrent async saves just writes the same
    value twice)."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    mode = _CKPT_MODES.get(ckpt_dir)
    if mode is None:
        import stat as _stat
        import uuid as _uuid

        p = os.path.join(ckpt_dir, f".singa-tpu-mode-{_uuid.uuid4().hex}")
        fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o666)
        try:
            mode = _stat.S_IMODE(os.fstat(fd).st_mode)
        finally:
            os.close(fd)
            try:
                os.unlink(p)
            except OSError:
                pass
        _CKPT_MODES[ckpt_dir] = mode
    return mode

# registry of graph runners (for Device.ResetGraph / PrintTimeProfiling)
_graph_runners = []


def _key_digest(key, width=96) -> str:
    """Compact, human-scannable form of a graph-cache key for trace
    args (the full nested tuple can run to kilobytes)."""
    s = str(key)
    return s if len(s) <= width else s[:width - 3] + "..."


def _cost_args(cost) -> dict:
    """Scalar entries of an XLA cost-analysis table, keyed safely for
    trace span args (spaces -> underscores); {} when unavailable."""
    c = cost[0] if isinstance(cost, (list, tuple)) and cost else cost
    if not isinstance(c, dict):
        return {}
    out = {}
    for k in ("flops", "bytes accessed", "transcendentals",
              "optimal_seconds"):
        v = c.get(k)
        if isinstance(v, (int, float)):
            out[k.replace(" ", "_")] = float(v)
    return out


def _clear_compiled_caches(device=None):
    for r in _graph_runners:
        r.clear()


def _compiled_cost_tables(device=None):
    out = []
    for r in _graph_runners:
        out.extend(r.cost_tables())
    return out


class Model(layer.Layer):
    """Subclass and override ``forward`` and ``train_one_batch`` (reference
    contract; see examples/)."""

    def __init__(self):
        super().__init__()
        self._optimizer = None
        self.graph_mode = False
        self.sequential = False
        self._graph_runner = None
        self.dist = False
        # GSPMD model-parallel plan (parallel/sharding.ShardingPlan):
        # when set, graph mode jits the step over globally-shaped arrays
        # laid out per the plan (tp/sp/pp/ep + dp), letting XLA's SPMD
        # partitioner insert the collectives.  Orthogonal to `dist`
        # (the reference-parity shard_map DistOpt path).
        self.sharding_plan = None
        # distributed output reassembly: "auto" (scalars -> cross-replica
        # mean, others -> merge per-rank batch), "stack" (raw (W, ...)),
        # or a list/tuple of per-output leaf specs from
        # {"mean", "concat", "stack"} matching the flattened structure of
        # train_one_batch's return value — the explicit form for outputs
        # that are neither scalars nor batch-leading (e.g. RNN hidden
        # states shaped (L, B/W, H), which "auto" would merge wrongly)
        self.dist_outputs = "auto"

    # -- reference API -----------------------------------------------------
    def compile(self, inputs, is_train=True, use_graph=False, sequential=False):
        """Initialize params with a dummy forward over ``inputs`` and fix
        the execution mode (reference: model.Model.compile)."""
        assert isinstance(inputs, (list, tuple)), "inputs must be a list"
        self.train(is_train)
        # name the layer tree before the dummy forward so params are
        # created with unique hierarchical names
        self.set_name(self.name)
        # dummy forward creates params eagerly (reference does the same)
        prev = autograd.training
        autograd.set_training(False)
        try:
            self.forward(*inputs)
        finally:
            autograd.set_training(prev)
        self._initialized = True
        # params created during the dummy forward get their final names now
        self.set_name(self.name)
        names = list(self.get_states().keys())
        assert len(names) == len(set(names)), (
            f"duplicate param/state names after compile: {names}")
        self.graph_mode = bool(use_graph)
        self.sequential = bool(sequential)
        if inputs:
            self.device = inputs[0].device
            self.device.EnableGraph(use_graph)
        if self.graph_mode:
            self._graph_runner = _GraphRunner(self)
            _graph_runners.append(self._graph_runner)
        if self._optimizer is not None and self.dist:
            self._optimizer.attach_model(self)

    def forward(self, *input):
        raise NotImplementedError

    def train_one_batch(self, *input, **kwargs):
        raise NotImplementedError

    def __call__(self, *input, **kwargs):
        if not self._initialized:
            # allow un-compiled eager use, like a plain Layer
            self.initialize(*input)
            self._initialized = True
        if autograd.training:
            return self._call_train_one_batch(*input, **kwargs)
        return self.forward(*input, **kwargs)

    def _call_train_one_batch(self, *args, **kwargs):
        if self.graph_mode and self._graph_runner is not None:
            return self._graph_runner.run(args, kwargs)
        return self.train_one_batch(*args, **kwargs)

    def train_n_batches(self, *args, n_steps=None, **kwargs):
        """Run K training steps in ONE host dispatch (round-5 addition;
        the reference dispatches per iteration — SURVEY.md §3.1 hot
        loop).  Two modes:

        * **stacked** (default): every ``Tensor`` argument carries a
          leading steps axis ``K`` (e.g. ``x: (K, B, D)``,
          ``y: (K, B)``) — K different prefetched batches;
        * **repeat** (``n_steps=K``): Tensor arguments are per-step
          shaped and the SAME device-resident batch feeds all K steps
          (useful for benchmarking and tight fitting loops without
          K-stacked input memory).

        Non-Tensor arguments are trace-time constants shared by every
        step.  The compiled program is ``lax.scan`` over the SAME step
        function graph mode traces for ``train_one_batch``, with
        donated state — so one tunnel round-trip buys K optimizer
        updates, which makes small latency-bound models (MLP,
        char-RNN) compute-bound instead of paying one host RTT per
        step.

        Returns ``train_one_batch``'s outputs with a leading K axis on
        every leaf (a scalar loss becomes a ``(K,)`` loss history;
        mind the memory if the model returns logits and K is large).
        Identical math to K single steps: the PRNG key, optimizer step
        counter and schedules advance inside the scan exactly as they
        would across K separate dispatches (tests/test_model.py asserts
        parity)."""
        if not (self.graph_mode and self._graph_runner is not None):
            raise ValueError(
                "train_n_batches requires compile(..., use_graph=True) "
                "— the multi-step scan only exists inside the compiled "
                "graph step")
        if not autograd.training:
            # mirror __call__'s gate: in eval mode the step would trace
            # without taping and still mutate params K times
            raise ValueError(
                "train_n_batches requires training mode (call "
                "model.train() first); the model is in eval mode")
        ts = [a for a in args if isinstance(a, Tensor)] + \
            [v for v in kwargs.values() if isinstance(v, Tensor)]
        if not ts:
            raise ValueError("train_n_batches needs at least one Tensor "
                             "input (the leading dim is the step count)")
        if n_steps is not None:
            if int(n_steps) < 1:
                raise ValueError(f"n_steps must be >= 1, got {n_steps}")
            return self._graph_runner.run(args, kwargs,
                                          n_steps=int(n_steps),
                                          repeat=True)
        for t in ts:
            if len(t.shape) == 0:
                raise ValueError(
                    "a 0-d Tensor argument cannot carry a steps axis; "
                    "pass it as a plain Python scalar (trace-time "
                    "constant) or use repeat mode (n_steps=K)")
        k = ts[0].shape[0]
        for t in ts:
            if t.shape[0] != k:
                raise ValueError(
                    f"all Tensor inputs must share the leading steps "
                    f"dim: got {t.shape[0]} vs {k}")
        if k < 1:
            raise ValueError(f"steps dim must be >= 1, got {k}")
        return self._graph_runner.run(args, kwargs, n_steps=int(k))

    def train(self, mode=True):
        self.training = bool(mode)
        autograd.set_training(mode)

    def eval(self):
        self.train(False)

    def set_sharding_plan(self, plan):
        """Attach a parallel.sharding.ShardingPlan; requires graph mode
        (GSPMD layouts only exist inside the compiled step).  Mutually
        exclusive with DistOpt's shard_map path."""
        if plan is not None and self.dist:
            raise ValueError(
                "sharding_plan and DistOpt are mutually exclusive: DistOpt "
                "runs the reference-parity shard_map data-parallel path; "
                "with a plan, use a plain optimizer — data parallelism "
                "comes from the mesh's 'data' axis")
        self.sharding_plan = plan
        if self._graph_runner is not None:
            # executables traced without the plan (or with another plan)
            # have the wrong layouts baked in
            self._graph_runner.clear()

    def set_optimizer(self, optimizer):
        dist = getattr(optimizer, "is_distributed", False)
        if dist and self.sharding_plan is not None:
            raise ValueError(
                "sharding_plan and DistOpt are mutually exclusive (see "
                "set_sharding_plan); use a plain optimizer with a plan")
        self._optimizer = optimizer
        self.dist = dist
        if self._graph_runner is not None:
            # executables bake the old optimizer's hyperparameters (read
            # at trace time) and its state materialization; a swapped
            # optimizer must recompile — and clearing here (like
            # set_sharding_plan) also defuses CPython id-reuse matching
            # a stale cache entry
            self._graph_runner.clear()

    @property
    def optimizer(self):
        return self._optimizer

    @optimizer.setter
    def optimizer(self, opt):
        self.set_optimizer(opt)

    def set_states(self, states: dict):
        """Layer.set_states plus decode-cache invalidation: the KV-decode
        session cache (models/gpt2_decode.extract_params) holds strong
        refs to the weight buffers it was built from, so after a weight
        swap the SUPERSEDED copy would stay pinned in device memory
        until the next generate call rebuilt the entry (ADVICE round
        5).  Dropping the entry here releases the old buffers
        immediately; the id-keyed signature already guaranteed the
        stale entry could never be *served*, only *retained*."""
        super().set_states(states)
        self.__dict__.pop("_decode_param_cache", None)

    # -- state (params + layer states + optimizer states) ------------------
    def persistent_tensors(self) -> dict:
        """Ordered name->Tensor map of everything that survives across
        steps; the traced state of graph mode."""
        d = dict(sorted(self.get_states().items()))
        if self._optimizer is not None:
            for k, v in sorted(self._optimizer.state_tensors().items()):
                d[f"__opt__{k}"] = v
        return d

    # -- checkpointing (reference: save_states/load_states zip format,
    #    SURVEY.md §3.5/§5.4) ---------------------------------------------
    def save_states(self, fpath, aux_states=None, async_save=False,
                    retry=None):
        """Zip of one .npy per state tensor + optimizer state + aux.

        ``async_save=True`` (beyond reference parity — the TPU-native
        upgrade orbax calls async checkpointing): the state is CAPTURED
        at call time as fresh DEVICE-SIDE copies (``jnp.copy`` — an
        async on-device op, so this returns without waiting), while the
        device→host transfer and zip write run in a background thread.
        The copies are essential, not just an optimization: graph mode
        compiles the step with donated state buffers, so the *original*
        arrays are deleted by the very next training step.  Returns an
        ``AsyncSaveHandle``; call ``.wait()`` before relying on the
        file (exceptions re-raise there; a fire-and-forget failure is
        logged at thread exit and counted in
        ``checkpoint.async_failures``).

        ``retry``: an optional
        :class:`~singa_tpu.resilience.retry.RetryPolicy` — transient
        write I/O retries with backoff (sync and async paths alike),
        counted under ``resilience.retries{site=checkpoint.write}``."""
        def snap(a):
            if not async_save:
                return a
            if isinstance(a, jax.Array) and not a.is_fully_addressable:
                # multi-host sharded state: the collective fetch must
                # happen on THIS thread (SPMD lockstep — a background
                # thread would deadlock the other processes)
                return _host_array(a)
            return jnp.copy(a)  # shield from graph-mode buffer donation

        with _trace.span("snapshot/capture", cat="snapshot",
                         path=str(fpath), async_save=bool(async_save)):
            captured = {k: snap(v.data)
                        for k, v in self.get_states().items()}
            if self._optimizer is not None:
                # state_tensors (not get_states): keep the transfer off
                # this thread; snap() shields the buffers from donation
                for k, v in self._optimizer.state_tensors().items():
                    captured[f"__opt__{k}"] = snap(v.data)
            if aux_states:
                for k, v in aux_states.items():
                    captured[f"__aux__{k}"] = np.asarray(v)

        def _write():
            with _trace.span("snapshot/write", cat="snapshot",
                             path=str(fpath), tensors=len(captured),
                             async_save=bool(async_save)):
                if retry is None:
                    _write_inner()
                else:
                    from .resilience.retry import retry_call

                    retry_call(_write_inner, "checkpoint.write",
                               policy=retry)

        def _write_inner():
            _faults.check("checkpoint.write")
            states = {k: _host_array(v) for k, v in captured.items()}
            # unique temp per call: two overlapping async saves to the
            # same fpath must not interleave writes into one temp file
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(fpath) + ".",
                suffix=".tmp",
                dir=os.path.dirname(os.path.abspath(fpath)) or ".",
            )
            try:
                # mkstemp creates 0600; restore the umask-derived mode so
                # the checkpoint stays as readable as a plain open()
                os.fchmod(fd, _ckpt_mode(
                    os.path.dirname(os.path.abspath(fpath)) or "."))
                with os.fdopen(fd, "wb") as fh:
                    with zipfile.ZipFile(fh, "w", zipfile.ZIP_DEFLATED) as zf:
                        for k, v in states.items():
                            buf = _io.BytesIO()
                            np.save(buf, v)
                            zf.writestr(k + ".npy", buf.getvalue())
                os.replace(tmp, fpath)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        if not async_save:
            _write()
            return None
        return AsyncSaveHandle(_write)

    def load_states(self, fpath):
        _faults.check("checkpoint.read")
        aux = {}
        opt_states = {}
        states = {}
        with zipfile.ZipFile(fpath, "r") as zf:
            for info in zf.namelist():
                k = info[:-len(".npy")]
                arr = np.load(_io.BytesIO(zf.read(info)), allow_pickle=False)
                if k.startswith("__aux__"):
                    aux[k[len("__aux__"):]] = arr
                elif k.startswith("__opt__"):
                    opt_states[k[len("__opt__"):]] = arr
                else:
                    states[k] = arr
        self.set_states(states)
        if self._optimizer is not None and opt_states:
            self._optimizer.set_states(opt_states)
        return aux

    # -- manager-aware checkpointing (single-file save_states/load_states
    #    parity above stays untouched) ------------------------------------
    def checkpoint_manager(self, root, keep=3, retry_policy=None):
        """A :class:`~singa_tpu.resilience.checkpoint.CheckpointManager`
        rooted at ``root``: step-numbered directories, strict-JSON
        manifests with whole-file digests, last-``keep`` retention, and
        corruption fallback on restore (docs/RESILIENCE.md)."""
        from .resilience.checkpoint import CheckpointManager

        return CheckpointManager(root, keep=keep,
                                 retry_policy=retry_policy)

    def save_checkpoint(self, root, step, aux_states=None, keep=3,
                        manager=None):
        """Manager-aware save: one validated, manifested checkpoint
        directory for ``step`` under ``root`` (retention applied).
        Returns the committed directory path."""
        mgr = manager or self.checkpoint_manager(root, keep=keep)
        return mgr.save(self, step, aux_states=aux_states)

    def restore_latest_checkpoint(self, root, manager=None):
        """Manager-aware restore: loads the newest VALID checkpoint
        under ``root``, falling back past corrupt/truncated steps
        (``resilience.checkpoint_fallbacks``).  Returns
        ``(step, aux_states)``."""
        mgr = manager or self.checkpoint_manager(root)
        return mgr.restore_latest(self)


def _host_array(a) -> np.ndarray:
    """Device->host fetch mirroring tensor.to_numpy's multi-host path
    (process_allgather for cross-process sharded arrays)."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils as mh

        return np.asarray(mh.process_allgather(a, tiled=True))
    return np.asarray(a)


class AsyncSaveHandle:
    """Background checkpoint write started by
    ``Model.save_states(async_save=True)``.

    A fire-and-forget save that fails must not be SILENT: the thread
    logs the exception at exit and bumps ``checkpoint.async_failures``
    whether or not anyone ever calls ``wait()`` — ``wait()`` still
    re-raises (test-pinned), the telemetry is additive."""

    def __init__(self, fn):
        import threading

        self._exc = None

        def run():
            try:
                fn()
            except BaseException as e:  # re-raised on wait()
                self._exc = e
                _obs_registry().counter(
                    "checkpoint.async_failures",
                    help="async checkpoint writes that failed in the "
                         "background thread").inc()
                from .utils.logging import get_channel

                get_channel("checkpoint").error(
                    "async checkpoint save failed (call wait() to "
                    "re-raise): %r", e)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in progress")
        if self._exc is not None:
            raise self._exc

    def done(self):
        return not self._thread.is_alive()


class _GraphRunner:
    """Compiles/replays ``train_one_batch`` (see module docstring)."""

    def __init__(self, model: Model):
        self.model = model
        self._compiled = {}  # key -> (jit_fn, state_names)
        self._plan_layouts = {}  # key -> (names, state/in/rng shardings)
        self._warm_keys = set()  # step signatures already state-probed
        # observe: compile-cache hit/miss + step counters (process-wide
        # registry; cached here so the hot replay path pays one integer
        # add, not a registry lookup)
        reg = _obs_registry()
        self._m_hit = reg.counter(
            "graph.cache_hit", help="graph-step executable replays")
        self._m_miss = reg.counter(
            "graph.cache_miss", help="graph-step compiles (new signature)")
        self._m_steps = reg.counter(
            "train.steps", help="optimizer steps dispatched via graph mode")

    def clear(self):
        self._compiled.clear()
        self._plan_layouts.clear()
        self._warm_keys.clear()

    def cost_tables(self):
        """XLA cost analysis per compiled step (feeds
        Device.PrintTimeProfiling, the rebuild of the reference's per-op
        CUDA-event profiling)."""
        out = []
        for key, entry in self._compiled.items():
            cost = entry[2] if len(entry) > 2 else None
            if cost:
                out.append((str(key), cost))
        return out

    def _abstract_key(self, args, kwargs):
        def sig(v):
            if isinstance(v, Tensor):
                return ("T", tuple(v.shape), str(np.dtype(v.data.dtype)))
            return ("V", v)

        # Trace-time globals are baked into the executable, so they must
        # be part of the cache key or toggling them after compile would
        # silently replay a stale program (round-2 verdict: amp.enable()
        # after compile kept running the fp32 step).  Covered here: the
        # amp compute dtype, the training flag, and the DistOpt flag.
        # Optimizer and sharding-plan REPLACEMENT is handled by their
        # setters clearing this cache (an id() in the key would be
        # vulnerable to CPython id reuse matching a stale entry);
        # optimizer hyperparameter SCHEDULES flow through the traced
        # step counter, so they do not need to be keyed.
        from . import amp
        m = self.model
        globals_sig = (
            str(amp.compute_dtype()),
            autograd.training,
            m.dist,
        )
        return (
            tuple(sig(a) for a in args),
            tuple(sorted((k, sig(v)) for k, v in kwargs.items())),
            globals_sig,
        )

    def _slice_step0(self, args, kwargs):
        """Per-step view of multi-step (K-leading) inputs: Tensor args
        sliced at step 0 (shape/dtype carriers for the abstract key,
        state probe, and step-builder structure)."""
        dev = self.model.device

        def sl(v):
            if isinstance(v, Tensor):
                return tensor._wrap(v.data[0], dev)
            return v

        return (tuple(sl(a) for a in args),
                {k: sl(v) for k, v in kwargs.items()})

    def run(self, args, kwargs, n_steps=None, repeat=False):
        model = self.model
        # multi-step: key/probe/build on the per-step slice; the leading
        # K axis lives only in the scan's xs.  repeat mode feeds the
        # same per-step-shaped batch to every scan iteration, so inputs
        # have NO leading steps axis (lead stays 0).
        if n_steps is None or repeat:
            key_args, key_kwargs = args, kwargs
        else:
            key_args, key_kwargs = self._slice_step0(args, kwargs)
        lead = 0 if (n_steps is None or repeat) else 1   # inputs
        out_lead = 0 if n_steps is None else 1           # scan-stacked ys
        key = self._abstract_key(key_args, key_kwargs)
        if n_steps is not None:
            key = key + (("__steps__", n_steps, repeat),)
        if key not in self._warm_keys:
            # Materialize lazily-created optimizer state (momentum buffers,
            # sparse residuals) by abstractly evaluating one step — no
            # compile, no execution; new state starts at zero, which is
            # exactly the optimizers' init.  The reference instead executes
            # its first graph iteration eagerly while recording; on this
            # backend eager dispatch compiles every op separately, so the
            # abstract probe saves minutes on large models.  Keyed per
            # step signature: a later call with a DIFFERENT dist-option
            # kwarg creates NEW optimizer state (e.g. sparse residuals)
            # that must be materialized too, or it would be left holding
            # dead tracers from its first trace.
            self._materialize_state(key_args, key_kwargs)
            self._warm_keys.add(key)
        state = model.persistent_tensors()
        names = list(state.keys())
        tensors = [state[n] for n in names]
        dev = model.device

        in_arrays = [a.data for a in args if isinstance(a, Tensor)]
        in_arrays += [v.data for k, v in sorted(kwargs.items())
                      if isinstance(v, Tensor)]
        if model.sharding_plan is not None and not model.dist:
            # GSPMD path: lay out state + inputs per the plan; XLA's SPMD
            # partitioner inserts every collective (dp grad psum, tp
            # all-reduce pairs, ep all-to-all); only ring attention and
            # the pipeline use explicit shard_map collectives.
            plan = model.sharding_plan
            if plan.input_specs is None:
                # "auto" input layout shards (per-step) dim 0 over data;
                # reject non-divisible batches instead of silently
                # replicating (explicit input_specs is the override for
                # genuinely non-batch-leading inputs)
                dp = plan.axis_size("data")
                for a in in_arrays:
                    if a.ndim - lead >= 1 and a.shape[lead] % dp != 0:
                        raise ValueError(
                            f"input dim {lead} ({a.shape[lead]}) not "
                            f"divisible by data-axis size {dp}; pass "
                            f"ShardingPlan(input_specs=...) for non-batch "
                            f"inputs")

            def in_spec(a, i):
                # per-step spec, prefixed with the (unsharded) steps axis
                # for multi-step stacked inputs
                if lead:
                    per = jax.ShapeDtypeStruct(a.shape[1:], a.dtype)
                    return P(None, *plan.spec_for_input(per, i))
                return plan.spec_for_input(a, i)

            layout = self._plan_layouts.get(key)
            if layout is None or layout[0] != names:
                param_specs = {
                    n: s for n, t in model.get_params().items()
                    if (s := getattr(t, "partition_spec", None)) is not None
                }
                layout = (names, [
                    plan.sharding(plan.spec_for_state(n, t, param_specs))
                    for n, t in zip(names, tensors)
                ], [
                    plan.sharding(in_spec(a, i))
                    for i, a in enumerate(in_arrays)
                ], plan.sharding(P()))
                self._plan_layouts[key] = layout
            _, state_sh, in_sh, rep = layout
            state_arrays = [jax.device_put(t.data, s)
                            for t, s in zip(tensors, state_sh)]
            state_arrays.append(jax.device_put(dev._rng_key, rep))
            in_arrays = [jax.device_put(a, s)
                         for a, s in zip(in_arrays, in_sh)]
        elif model.dist:
            # replicate state over the mesh, shard batch inputs on dim 0
            from jax.sharding import NamedSharding

            comm = model._optimizer.communicator
            mesh, axis = comm.mesh, comm.axis_name
            nproc = jax.process_count()
            if nproc == 1:
                for a in in_arrays:
                    if a.ndim - lead >= 1 \
                            and a.shape[lead] % comm.world_size != 0:
                        raise ValueError(
                            f"global batch dim {a.shape[lead]} not "
                            f"divisible by world size {comm.world_size}")
                rep = NamedSharding(mesh, P())
                ranked = NamedSharding(mesh, P(axis))
                state_arrays = [
                    jax.device_put(t.data,
                                   ranked if "__residual__" in n else rep)
                    for n, t in zip(names, tensors)
                ]
                state_arrays.append(jax.device_put(dev._rng_key, rep))

                def dist_spec(a):
                    # batch axis on the mesh; the steps axis (multi-step)
                    # stays unsharded so the scan slices per step
                    if a.ndim - lead >= 1:
                        return P(*([None] * lead), axis)
                    return P(*([None] * lead)) if lead else P()

                in_arrays = [
                    jax.device_put(a, NamedSharding(mesh, dist_spec(a)))
                    for a in in_arrays
                ]
            else:
                # MULTI-HOST (reference: each MPI rank feeds its own
                # shard): inputs are this process's LOCAL batch; state
                # is broadcast from process 0 (the reference's MPI
                # bcast) into one global replicated array.  After step 1
                # the state is already global (outputs of the global
                # step) and passes through untouched.
                state_arrays, in_arrays = self._globalize_multihost(
                    mesh, axis, names, tensors, in_arrays, dev,
                    check=key not in self._compiled, lead=lead)
        else:
            state_arrays = [jax.device_put(t.data, dev.jax_device)
                            for t in tensors]
            state_arrays.append(jax.device_put(dev._rng_key, dev.jax_device))

        if model.sharding_plan is not None and not model.dist:
            # activate the plan while tracing so constrain() ops pin
            # GSPMD layouts (they are identity outside planned traces)
            from .parallel.sharding import _PlanActive
            trace_ctx = _PlanActive()
        else:
            import contextlib
            trace_ctx = contextlib.nullcontext()
        with trace_ctx:
            fresh_compile = (key not in self._compiled
                             or self._compiled[key][1] != names)
            if fresh_compile:
                self._m_miss.inc()
                _trace.event("graph/cache_miss", cat="train",
                             key=_key_digest(key))
                with _trace.span("graph/compile", cat="train",
                                 key=_key_digest(key),
                                 steps=n_steps or 1) as sp:
                    fn = self._build(key_args, key_kwargs, names,
                                     n_steps=n_steps, repeat=repeat)
                    cost = None
                    try:
                        compiled = fn.lower(state_arrays,
                                            in_arrays).compile()
                        cost = compiled.cost_analysis()
                        fn = compiled
                    except Exception:
                        pass  # fall back to on-demand jit compile
                    self._compiled[key] = (fn, names, cost)
                    sp.set(**_cost_args(cost))
            else:
                self._m_hit.inc()
            self._m_steps.inc(n_steps or 1)
            if _faults._armed:
                # chaos hook for the train dispatch path; disarmed the
                # replay loop pays this one module-flag read
                _faults.check("train.step")
            fn = self._compiled[key][0]
            # watchdog heartbeat around the dispatch (two clock calls,
            # only while monitoring is on): liveness always; step time
            # only for replays — a compile dispatch is minutes against
            # milliseconds and would poison the EWMA anomaly estimator
            # and the per-process straggler histogram
            _mon = _monitor.active()
            _hb_t0 = _time.perf_counter() if _mon else 0.0
            with _trace.span("train/step", cat="train",
                             steps=n_steps or 1):
                # host-side dispatch time: device execution is async, so
                # the span closes when XLA accepts the work, not when the
                # step finishes — the caller's readback sync (loss fetch)
                # carries the device tail
                new_state, out_tree = fn(state_arrays, in_arrays)
            if _mon:
                _monitor.heartbeat(
                    "train", step_time=_time.perf_counter() - _hb_t0,
                    steps=n_steps or 1, fresh_compile=fresh_compile)
        for t, a in zip(tensors, new_state[:-1]):
            t.data = a
            t.creator = None
        dev._rng_key = new_state[-1]
        if model.dist or model.sharding_plan is not None:
            # the step returns the PRNG key replicated over the mesh;
            # re-commit it to the device's own chip so later EAGER rng
            # use (e.g. initializing another model) doesn't propagate
            # multi-device placement.  Multi-host: the global replicated
            # array isn't device_puttable directly — its value is any
            # local shard.
            k = dev._rng_key
            if isinstance(k, jax.Array) and not k.is_fully_addressable:
                k = np.asarray(k.addressable_shards[0].data)
            dev._rng_key = jax.device_put(k, dev.jax_device)
        if model.dist and model.dist_outputs != "stack":
            # Outputs come back stacked per-rank (see _build).  The "auto"
            # reassembly contract handles only UNAMBIGUOUS leaves: a
            # per-rank scalar, now (W,), becomes the cross-replica mean
            # (the global loss); a leaf whose dim 1 equals the per-rank
            # batch merges its first two dims, (W, B/W, ...) -> (B, ...).
            # Anything else (e.g. RNN hidden states shaped (L, B/W, H))
            # RAISES with the fix — silently guessing a merge corrupted
            # such outputs before (round-2 verdict).  Explicit per-leaf
            # specs via model.dist_outputs = ["mean"/"concat"/"stack",
            # ...] (flattened output order), or "stack" for raw (W, ...)
            # per-rank stacks.  Known contract boundary: a NON-batch
            # per-rank vector that coincidentally has per-rank-batch
            # length still merges — only explicit specs can express
            # that; the dist input path itself requires batch-leading
            # dim-0 inputs (divisibility check above), so per_rank
            # derived from input dim 0 is consistent with the sharding.
            W = model._optimizer.communicator.world_size
            global_b = next(
                (a.shape[lead] for a in in_arrays
                 if getattr(a, "ndim", 0) - lead >= 1), None)
            per_rank = global_b // W if global_b else None

            def merge(a):
                # fold the per-rank axis into the batch axis (both sit
                # after the optional leading steps axis of multi-step)
                ol = out_lead
                return a.reshape(a.shape[:ol]
                                 + (a.shape[ol] * a.shape[ol + 1],)
                                 + a.shape[ol + 2:])

            def unstack_auto(a):
                if a.ndim == 1 + out_lead:
                    return (jnp.mean(a, axis=out_lead) if out_lead
                            else jnp.mean(a))
                if per_rank is not None and a.ndim >= 2 + out_lead \
                        and a.shape[out_lead + 1] == per_rank:
                    return merge(a)
                per_leaf = tuple(a.shape[out_lead + 1:])
                raise ValueError(
                    f"cannot auto-reassemble distributed output of "
                    f"per-rank shape {per_leaf}: its leading dim is "
                    f"neither a scalar nor the per-rank batch "
                    f"({per_rank}); set model.dist_outputs to a list of "
                    f"per-leaf specs from {{'mean', 'concat', 'stack'}} "
                    f"(flattened train_one_batch output order), or "
                    f"'stack' for raw (W, ...) stacks")

            if isinstance(model.dist_outputs, (list, tuple)):
                leaves, treedef = jax.tree.flatten(out_tree)
                specs = list(model.dist_outputs)
                if len(specs) != len(leaves):
                    raise ValueError(
                        f"dist_outputs has {len(specs)} specs but "
                        f"train_one_batch returned {len(leaves)} outputs")
                applied = []
                for spec, a in zip(specs, leaves):
                    if spec == "mean":
                        applied.append(jnp.mean(a, axis=out_lead))
                    elif spec == "concat":
                        applied.append(merge(a))
                    elif spec == "stack":
                        applied.append(a)
                    else:
                        raise ValueError(f"unknown dist_outputs spec "
                                         f"{spec!r}")
                out_tree = jax.tree.unflatten(treedef, applied)
            else:
                out_tree = jax.tree.map(unstack_auto, out_tree)
        return jax.tree.map(
            lambda a: tensor._wrap(a, dev),
            out_tree,
        )

    @staticmethod
    def _globalize_multihost(mesh, axis, names, tensors, in_arrays, dev,
                             check, lead=0):
        """Lift process-local arrays to global arrays over the
        multi-host mesh (jax.distributed runtime).

        Replicated state is BROADCAST from process 0 (the reference's
        MPI bcast of initial params / NCCL id): hosts whose local init
        diverged — a checkpoint loaded on one host, host-dependent
        seeds — start consistent instead of silently training on
        per-shard-different 'replicated' values.  Per-rank sharded
        state (DistOpt residuals, global shape (W, ...)): each host
        contributes the row blocks of ITS devices per the mesh's
        device order.  Batch inputs: the local batch becomes this
        host's slice of the global batch dim.

        ``check``: on a new step signature, first verify every host
        shows the same input shapes — a ragged final batch would
        otherwise compile per-host-different programs and deadlock in
        the collectives with no diagnostic."""
        from jax.experimental import multihost_utils as mh

        pid = jax.process_index()

        if check:
            digest = np.zeros(64, np.int64)
            flat = [d for a in in_arrays
                    for d in (a.ndim, *a.shape)][:63]
            digest[0] = len(flat)
            digest[1:1 + len(flat)] = flat
            gathered = mh.process_allgather(digest)  # (nproc, 64)
            if not (gathered == gathered[0]).all():
                raise ValueError(
                    "multi-host input shapes disagree across processes "
                    f"(shape digests: {gathered.tolist()}); every host "
                    "must feed the same LOCAL batch shape each step — "
                    "drop or pad the ragged final batch")

        def is_global(a):
            return (isinstance(a, jax.Array)
                    and len(a.sharding.device_set) == mesh.devices.size)

        # rows of a (W, ...) per-rank array owned by this host, in the
        # mesh's device order (host_local_array_to_global_array stitches
        # shards in that order)
        my_dev_idx = [i for i, d in enumerate(mesh.devices.flat)
                      if d.process_index == pid]
        if my_dev_idx != list(range(my_dev_idx[0], my_dev_idx[-1] + 1)):
            # must hold under `python -O` too: a non-contiguous order
            # would silently stitch residual row blocks wrongly
            raise ValueError(
                "this process's devices are not contiguous in the mesh; "
                "build the data axis in process order")

        state_arrays = []
        for n, t in zip(names, tensors):
            a = t.data
            if is_global(a):
                state_arrays.append(a)
                continue
            host = np.asarray(a)
            if "__residual__" in n:
                per_dev = host.shape[0] // mesh.devices.size
                host = host[my_dev_idx[0] * per_dev:
                            (my_dev_idx[-1] + 1) * per_dev]
                spec = P(axis)
            else:
                host = mh.broadcast_one_to_all(host)
                spec = P()
            state_arrays.append(
                mh.host_local_array_to_global_array(host, mesh, spec))
        key = dev._rng_key
        state_arrays.append(
            key if is_global(key) else
            mh.host_local_array_to_global_array(
                np.asarray(mh.broadcast_one_to_all(np.asarray(key))),
                mesh, P()))
        n_local = jax.local_device_count()
        global_in = []
        for a in in_arrays:
            if is_global(a):
                global_in.append(a)
                continue
            if a.ndim - lead >= 1:
                if a.shape[lead] % n_local != 0:
                    raise ValueError(
                        f"local batch dim {a.shape[lead]} not divisible "
                        f"by local device count {n_local}")
                # lead=1: multi-step stacked input — the steps axis stays
                # replicated; the per-step batch axis shards over ranks
                spec = P(*([None] * lead), axis)
            else:
                spec = P(*([None] * lead)) if lead else P()
            global_in.append(
                mh.host_local_array_to_global_array(np.asarray(a), mesh,
                                                    spec))
        return state_arrays, global_in

    def _materialize_state(self, args, kwargs):
        model = self.model
        dev = model.device
        before = dict(model.persistent_tensors())
        saved = [(t, t.data) for t in before.values()]
        saved_key = dev._rng_key
        tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        tensor_kw = sorted(k for k, v in kwargs.items()
                           if isinstance(v, Tensor))
        in_arrays = [args[i].data for i in tensor_idx] + \
            [kwargs[k].data for k in tensor_kw]

        def probe(in_arrays):
            call_args = list(args)
            for i, arr in zip(tensor_idx, in_arrays[:len(tensor_idx)]):
                call_args[i] = tensor._wrap(arr, dev)
            call_kwargs = dict(kwargs)
            for k, arr in zip(tensor_kw, in_arrays[len(tensor_idx):]):
                call_kwargs[k] = tensor._wrap(arr, dev)
            model.train_one_batch(*call_args, **call_kwargs)
            return jnp.zeros(())

        try:
            jax.eval_shape(probe, in_arrays)
        finally:
            for t, a in saved:
                t.data = a
                t.creator = None
            dev._rng_key = saved_key
        # tensors created during the probe hold dead abstract tracers;
        # zero-fill them (momenta/residuals/step counters all start at 0)
        for name, t in model.persistent_tensors().items():
            if name not in before:
                aval = getattr(t.data, "aval", t.data)
                t.data = jax.device_put(
                    jnp.zeros(aval.shape, aval.dtype), dev.jax_device)
                t.creator = None

    def _build(self, args, kwargs, names, n_steps=None, repeat=False):
        """Build the jitted step.  ``n_steps``: wrap the step in a
        ``lax.scan`` over K stacked batches (train_n_batches) — one
        executable, one dispatch, K optimizer updates; with ``repeat``
        the same per-step batch feeds every iteration instead of
        scanning stacked xs.  ``args``/``kwargs`` are always PER-STEP
        shaped (the caller slices multi-step inputs), so the step
        closure and the shard_map specs below are identical in all
        modes; only the scan differs."""
        model = self.model
        dev = model.device
        tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        tensor_kw = sorted(k for k, v in kwargs.items() if isinstance(v, Tensor))

        def step(state_arrays, in_arrays):
            state = model.persistent_tensors()
            tensors = [state[n] for n in names]
            saved = [(t, t.data) for t in tensors]
            saved_key = dev._rng_key
            try:
                for t, a in zip(tensors, state_arrays[:-1]):
                    t.data = a
                    t.creator = None
                dev._rng_key = state_arrays[-1]
                call_args = list(args)
                for i, arr in zip(tensor_idx, in_arrays[:len(tensor_idx)]):
                    call_args[i] = tensor._wrap(arr, dev)
                    call_args[i].requires_grad = False
                call_kwargs = dict(kwargs)
                for k, arr in zip(tensor_kw, in_arrays[len(tensor_idx):]):
                    call_kwargs[k] = tensor._wrap(arr, dev)
                    call_kwargs[k].requires_grad = False
                out = model.train_one_batch(*call_args, **call_kwargs)
                new_state = [t.data for t in tensors] + [dev._rng_key]
                out_tree = jax.tree.map(
                    lambda v: v.data if isinstance(v, Tensor) else v, out,
                    is_leaf=lambda v: isinstance(v, Tensor),
                )
                return new_state, out_tree
            finally:
                for t, a in saved:
                    t.data = a
                    t.creator = None
                dev._rng_key = saved_key

        def finish(step_fn):
            if n_steps is None:
                return jax.jit(step_fn, donate_argnums=(0,))

            if repeat:
                def multi(state_arrays, in_arrays):
                    # same device-resident batch every iteration
                    return jax.lax.scan(
                        lambda st, _: step_fn(st, in_arrays),
                        state_arrays, None, length=n_steps)
            else:
                def multi(state_arrays, stacked_in):
                    # scan slices each stacked input's leading steps
                    # axis; the step's (new_state, out_tree) contract is
                    # exactly scan's (carry, y), so outputs stack to
                    # (K, ...) leaves
                    return jax.lax.scan(step_fn, state_arrays, stacked_in)

            return jax.jit(multi, donate_argnums=(0,))

        if not model.dist:
            return finish(step)

        # DistOpt: run the step per-rank under shard_map — SINGA's SPMD
        # programming model recovered inside a single-controller runtime.
        # Replicated state (params, optimizer moments) uses P(); per-rank
        # accumulators (DistOpt residuals, global shape (W, ...)) are
        # sharded P(axis) so each rank keeps a private slice; layer state
        # that legitimately diverges per rank (BN running stats computed
        # on the local shard) is pmean'd — tiny arrays, and strictly
        # better-defined than the reference's "rank 0's copy wins".
        comm = model._optimizer.communicator
        mesh, axis = comm.mesh, comm.axis_name
        state_specs = [
            P(axis) if "__residual__" in n else P() for n in names
        ] + [P()]  # trailing entry: PRNG base key
        layer_state_names = set(model.get_states()) - set(model.get_params())
        pmean_idx = [i for i, n in enumerate(names)
                     if n in layer_state_names]

        def rank_step(state_arrays, in_arrays):
            # advance the PRNG base once (replicated), give each rank an
            # independent subkey so dropout masks differ across ranks
            base = state_arrays[-1]
            new_base, sub = jax.random.split(base)
            rank_key = jax.random.fold_in(sub, jax.lax.axis_index(axis))
            new_state, out_tree = step(
                list(state_arrays[:-1]) + [rank_key], in_arrays)
            new_state = list(new_state[:-1]) + [new_base]
            for i in pmean_idx:
                new_state[i] = jax.lax.pmean(new_state[i], axis)
            # stack every output with a leading per-rank axis so one
            # out_spec covers arbitrary train_one_batch return trees
            out_stacked = jax.tree.map(lambda a: jnp.expand_dims(a, 0),
                                       out_tree)
            return new_state, out_stacked

        in_tensors = [x for x in args if isinstance(x, Tensor)] \
            + [kwargs[k] for k in tensor_kw]
        in_tensor_specs = [
            P(axis) if t.data.ndim >= 1 else P() for t in in_tensors
        ]
        sharded = jax.shard_map(
            rank_step,
            mesh=mesh,
            in_specs=(state_specs, in_tensor_specs),
            out_specs=(state_specs, P(axis)),
            check_vma=False,
        )
        return finish(sharded)
