"""Mixed-precision policy — TPU-native bf16 compute with fp32 master
params.

The reference's half-precision story is wire-only (fp16 gradient
compression in ``Communicator::synchHalf``, SURVEY.md §2.1); compute
stays fp32 because V100-era cuDNN fp16 needs loss scaling and per-op
opt-in.  On TPU the natural equivalent is **bf16 compute**: same
exponent range as fp32 (no loss scaling needed), 2x MXU issue rate and
half the HBM traffic.  Policy (the standard one):

  * params stay fp32 ("master weights"; the optimizer already updates
    in fp32 — see ``opt.Optimizer._assign``);
  * MXU ops (conv / matmul / gemm) cast their inputs to bf16, so
    activations flow bf16 between layers;
  * normalization statistics and the softmax-cross-entropy loss are
    computed in fp32 (bf16's 8-bit mantissa is too coarse for
    variance/log-sum-exp);
  * gradients come back through the cast nodes as fp32 for fp32 params
    (jax.vjp of ``convert_element_type`` restores the input dtype), so
    optimizer state and the DistOpt wire path are unchanged.

Enable globally with ``amp.enable()`` (or ``set_compute_dtype``); graph
mode picks it up at the next (re)compile since the flag is read at trace
time.  Off by default — numerics match the reference's fp32 exactly.
"""

from __future__ import annotations

import jax.numpy as jnp

_compute_dtype = None  # None => full fp32 (policy off)


def enable(on=True):
    """Turn bf16 mixed-precision compute on/off."""
    set_compute_dtype(jnp.bfloat16 if on else None)


def set_compute_dtype(dtype):
    global _compute_dtype
    if dtype in (None, "float32", jnp.float32):
        _compute_dtype = None
    else:
        _compute_dtype = jnp.dtype(dtype)


def compute_dtype():
    """The MXU compute dtype, or None when the policy is off."""
    return _compute_dtype


def enabled() -> bool:
    return _compute_dtype is not None


def param_dtype(activation_dtype):
    """Dtype for a parameter created from an activation of the given
    dtype: under amp, bf16 activations still get fp32 master params."""
    if _compute_dtype is not None and \
            jnp.dtype(activation_dtype) == _compute_dtype:
        return jnp.float32
    return activation_dtype


def cast_in(*arrays):
    """Cast MXU-op inputs to the compute dtype (no-op when off).
    Integer arrays pass through untouched."""
    if _compute_dtype is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = tuple(
        a.astype(_compute_dtype)
        if a is not None and jnp.issubdtype(a.dtype, jnp.floating) else a
        for a in arrays
    )
    return out if len(out) != 1 else out[0]
