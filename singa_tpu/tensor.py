"""SINGA-shaped ``Tensor`` over ``jax.Array``.

Reference parity (apache/singa, paths unverified — SURVEY.md §2):
  - ``python/singa/tensor.py`` (~1.7k LoC): Python ``Tensor`` wrapping the
    SWIG ``CTensor``; numpy interop, operators, ``to_device``, module-level
    functional ops (``add``, ``mult``, ``softmax``, reductions, random
    fills, row/column ops...).
  - ``src/core/tensor/tensor.cc`` + ``tensor_math_{cpp,cuda}.h``: the C++
    tensor and its per-backend math dispatch (cuBLAS GEMM, CUDA kernels).

TPU-native design: the SWIG boundary and the C++ tensor disappear; one
Python class holds a ``jax.Array`` and every math op is a ``jnp``/``lax``
call, so the same code path serves eager mode and ``jax.jit`` tracing
(graph mode).  "In-place" SINGA ops (``+=``, ``SetValue``, ``copy_data``)
become functional *rebinds* of the underlying array — semantically
equivalent for SINGA programs, which never alias one buffer through two
tensors across a mutation (the scheduler would serialize them anyway).

Autograd bookkeeping (``creator``/``requires_grad``/``stores_grad``)
matches ``python/singa/tensor.py``; the tape lives in ``autograd.py``.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import device as device_module
from .device import get_default_device

# ---------------------------------------------------------------------------
# dtypes — SINGA's proto enum (core.proto kFloat32...) becomes plain numpy
# dtypes; names kept importable as tensor.float32 etc.
# ---------------------------------------------------------------------------
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64  # note: jax x64 is off by default; maps to float32
int8 = jnp.int8
uint8 = jnp.uint8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
bool_ = jnp.bool_

# SINGA proto-enum-style names for source compat
kFloat16 = float16
kFloat32 = float32
kInt = int32
kInt32 = int32
kInt64 = int64
kChar = int8
kUChar = uint8
kDouble = float64

_SINGA2DTYPE = {
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "int32": int32,
    "int64": int64,
}


def _asdtype(dt):
    if dt is None:
        return jnp.float32
    if isinstance(dt, str):
        return _SINGA2DTYPE.get(dt, np.dtype(dt).type)
    return dt


def _raw(x):
    """Unwrap Tensor → jax array; pass scalars/arrays through."""
    if isinstance(x, Tensor):
        return x.data
    return x


class Tensor:
    """A tensor on a singa device, wrapping a ``jax.Array`` (or a tracer
    while a graph-mode step is being traced).

    Mirrors python/singa/tensor.py's constructor signature (unverified).
    """

    __array_priority__ = 100  # make numpy defer to our reflected operators

    def __init__(
        self,
        shape=(),
        device=None,
        dtype=None,
        data=None,
        requires_grad=True,
        stores_grad=False,
        creator=None,
        name=None,
    ):
        """``dtype=None`` means float32 for fresh (zero-filled) tensors and
        "keep the data's dtype" when ``data`` is given; an explicit dtype
        always wins."""
        self.device = device if device is not None else get_default_device()
        want = _asdtype(dtype) if dtype is not None else None
        if data is None:
            arr = jnp.zeros(tuple(shape), dtype=want or jnp.float32)
            arr = jax.device_put(arr, self.device.jax_device)
        else:
            if isinstance(data, Tensor):
                arr = data.data
            elif isinstance(data, np.ndarray):
                arr = jax.device_put(jnp.asarray(data), self.device.jax_device)
            else:
                # jax array / tracer / python scalar
                arr = jnp.asarray(data)
            if want is not None and arr.dtype != np.dtype(want):
                arr = arr.astype(want)
        self.data = arr
        self.requires_grad = requires_grad
        self.stores_grad = stores_grad
        self.creator = creator
        self.name = name

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def T(self):
        return _wrap(jnp.transpose(self.data), self.device)

    def ndim(self):
        return self.data.ndim

    def is_empty(self):
        return self.size() == 0

    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    def memsize(self):
        return self.size() * self.data.dtype.itemsize

    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={np.dtype(self.data.dtype).name}, "
            f"device={type(self.device).__name__})"
        )

    # -- shape ops ---------------------------------------------------------
    def reshape(self, shape):
        """Returns a reshaped tensor (SINGA >=3 returns new tensor)."""
        return _wrap(jnp.reshape(self.data, tuple(shape)), self.device)

    def transpose(self, axes=None):
        return _wrap(jnp.transpose(self.data, axes), self.device)

    def squeeze(self, axis=None):
        return _wrap(jnp.squeeze(self.data, axis), self.device)

    def reset_like(self, t: "Tensor"):
        z = jnp.zeros(t.shape, dtype=t.data.dtype)
        if not _is_tracing(z):
            z = jax.device_put(z, self.device.jax_device)
        self.data = z
        return self

    def as_type(self, dtype):
        return _wrap(self.data.astype(_asdtype(dtype)), self.device)

    def astype(self, dtype):
        return self.as_type(dtype)

    # -- device movement ---------------------------------------------------
    def to_device(self, dev):
        """Move in place (SINGA Tensor::ToDevice mutates); returns self."""
        if not _is_tracing(self.data):
            self.data = jax.device_put(self.data, dev.jax_device)
        self.device = dev
        return self

    def to_host(self):
        """Move to host CPU (reference Tensor::ToHost) — explicitly a
        CppCPU, not the mutable default device, which may itself be an
        accelerator after set_default_device(tpu)."""
        return self.to_device(device_module.CppCPU())

    # -- fills / random ----------------------------------------------------
    def set_value(self, x, inplace=True):
        self.data = jnp.full(self.shape, x, dtype=self.data.dtype)
        return self

    def SetValue(self, x):  # C++-style alias used by reference scripts
        return self.set_value(x)

    def gaussian(self, mean=0.0, std=1.0):
        key = self.device.rng_key()
        self.data = mean + std * jax.random.normal(key, self.shape, dtype=jnp.float32)
        self.data = self.data.astype(_asdtype(self.dtype))
        return self

    def uniform(self, low=0.0, high=1.0):
        key = self.device.rng_key()
        self.data = jax.random.uniform(
            key, self.shape, dtype=jnp.float32, minval=low, maxval=high
        ).astype(_asdtype(self.dtype))
        return self

    def bernoulli(self, p):
        key = self.device.rng_key()
        self.data = jax.random.bernoulli(key, p, self.shape).astype(
            _asdtype(self.dtype)
        )
        return self

    # -- copies ------------------------------------------------------------
    def copy_from_numpy(self, np_array, offset=0):
        assert np_array.size == self.size(), "array size mismatch"
        self.data = jnp.asarray(
            np.ascontiguousarray(np_array, dtype=np.dtype(self.data.dtype)).reshape(
                self.shape
            )
        )
        return self

    def copy_data(self, t: "Tensor"):
        """Copy t's buffer into self (shape must match)."""
        assert t.shape == self.shape, f"shape mismatch {t.shape} vs {self.shape}"
        self.data = t.data.astype(self.data.dtype)
        return self

    def copy_from(self, t: "Tensor"):
        return self.copy_data(t)

    def clone(self):
        """Deep copy (reference Tensor::Clone copies the buffer).  The
        copy matters: graph-mode steps donate their state buffers to XLA,
        so an aliased buffer would be invalidated by the donor's next
        step."""
        data = self.data
        if not _is_tracing(data):
            data = jnp.array(data, copy=True)
        t = Tensor(
            device=self.device,
            data=data,
            requires_grad=self.requires_grad,
            stores_grad=self.stores_grad,
        )
        return t

    def copy(self):
        return self.clone()

    def deepcopy(self):
        return self.clone()

    # -- reductions / norms ------------------------------------------------
    def l1(self):
        return float(jnp.mean(jnp.abs(self.data)))

    def l2(self):
        # SINGA Tensor::L2 returns ||x||_2 / sqrt(n) (nrm2 / num elems? —
        # upstream divides by size; we match mean-style normalization).
        return float(jnp.linalg.norm(self.data.ravel()) / np.sqrt(self.size()))

    def sum(self, axis=None):
        return _wrap(jnp.sum(self.data, axis=axis), self.device)

    def mean(self, axis=None):
        return _wrap(jnp.mean(self.data, axis=axis), self.device)

    def max(self, axis=None):
        return _wrap(jnp.max(self.data, axis=axis), self.device)

    def min(self, axis=None):
        return _wrap(jnp.min(self.data, axis=axis), self.device)

    # -- arithmetic operators (eager, non-autograd — matches reference
    #    tensor.py, where operators go through tensor math not the tape) ---
    def __add__(self, x):
        return _wrap(self.data + _raw(x), self.device)

    __radd__ = __add__

    def __sub__(self, x):
        return _wrap(self.data - _raw(x), self.device)

    def __rsub__(self, x):
        return _wrap(_raw(x) - self.data, self.device)

    def __mul__(self, x):
        return _wrap(self.data * _raw(x), self.device)

    __rmul__ = __mul__

    def __truediv__(self, x):
        return _wrap(self.data / _raw(x), self.device)

    def __rtruediv__(self, x):
        return _wrap(_raw(x) / self.data, self.device)

    def __floordiv__(self, x):
        return _wrap(self.data // _raw(x), self.device)

    def __pow__(self, x):
        return _wrap(self.data ** _raw(x), self.device)

    def __neg__(self):
        return _wrap(-self.data, self.device)

    def __abs__(self):
        return _wrap(jnp.abs(self.data), self.device)

    def __matmul__(self, x):
        return _wrap(jnp.matmul(self.data, _raw(x)), self.device)

    # in-place ops rebind the array; under SINGA semantics the scheduler
    # serializes writers, so rebinding is observationally equivalent.
    def __iadd__(self, x):
        self.data = self.data + _raw(x)
        return self

    def __isub__(self, x):
        self.data = self.data - _raw(x)
        return self

    def __imul__(self, x):
        self.data = self.data * _raw(x)
        return self

    def __itruediv__(self, x):
        self.data = self.data / _raw(x)
        return self

    # comparisons return 0/1 float tensors like SINGA's LT/GT kernels
    def __lt__(self, x):
        return _wrap((self.data < _raw(x)).astype(jnp.float32), self.device)

    def __le__(self, x):
        return _wrap((self.data <= _raw(x)).astype(jnp.float32), self.device)

    def __gt__(self, x):
        return _wrap((self.data > _raw(x)).astype(jnp.float32), self.device)

    def __ge__(self, x):
        return _wrap((self.data >= _raw(x)).astype(jnp.float32), self.device)

    def __getitem__(self, idx):
        return _wrap(self.data[idx], self.device)

    def __float__(self):
        return float(self.data)

    def __int__(self):
        return int(self.data)


def _wrap(arr, dev=None) -> Tensor:
    t = Tensor.__new__(Tensor)
    t.data = arr
    t.device = dev if dev is not None else get_default_device()
    t.requires_grad = False
    t.stores_grad = False
    t.creator = None
    t.name = None
    return t


def _is_tracing(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# module-level functional API (reference: python/singa/tensor.py module
# functions, unverified list — implemented generously)
# ---------------------------------------------------------------------------

def from_numpy(np_array, device=None, requires_grad=False) -> Tensor:
    np_array = np.asarray(np_array)
    if np_array.dtype == np.float64:
        np_array = np_array.astype(np.float32)
    elif np_array.dtype == np.int64:
        # jax runs x32: jnp would truncate to int32 anyway, but via the
        # Tensor(dtype=int64) path that emits a per-call UserWarning;
        # downcast explicitly like float64 -> float32 above
        np_array = np_array.astype(np.int32)
    t = Tensor(
        shape=np_array.shape,
        device=device,
        dtype=np_array.dtype.type,
        data=np_array,
        requires_grad=requires_grad,
    )
    return t


def to_host(t):
    """Host COPY of t (reference: module-level tensor.to_host clones
    then moves — the input keeps its device; only the method form
    migrates in place)."""
    return t.clone().to_host()


def to_numpy(t) -> np.ndarray:
    arr = _raw(t)
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        # multi-host: a cross-process sharded array (e.g. DistOpt
        # residuals after a step) needs a collective fetch.  SPMD
        # lockstep: every process calls to_numpy at the same point, so
        # the allgather is safe.
        from jax.experimental import multihost_utils as mh

        return np.asarray(mh.process_allgather(arr, tiled=True))
    return np.asarray(jax.device_get(arr))


def from_raw_tensor(arr, device=None) -> Tensor:
    return _wrap(jnp.asarray(arr), device)


def sizeof(dtype) -> int:
    return np.dtype(_asdtype(dtype)).itemsize


def _unary(fn):
    def op(t):
        return _wrap(fn(_raw(t)), getattr(t, "device", None))

    return op


abs = _unary(jnp.abs)  # noqa: A001 - mirrors reference module name
exp = _unary(jnp.exp)
log = _unary(jnp.log)
sigmoid = _unary(jax.nn.sigmoid)
sign = _unary(jnp.sign)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
tanh = _unary(jnp.tanh)
ceil = _unary(jnp.ceil)
floor = _unary(jnp.floor)
round = _unary(jnp.round)  # noqa: A001
relu = _unary(jax.nn.relu)


def pow(t, x, out=None):  # noqa: A001
    r = _wrap(_raw(t) ** _raw(x), getattr(t, "device", None))
    if out is not None:
        out.data = r.data
        return out
    return r


def sum(t, axis=None, out=None):  # noqa: A001
    r = _wrap(jnp.sum(_raw(t), axis=axis), getattr(t, "device", None))
    if out is not None:
        out.data = r.data
        return out
    return r


def mean(t, axis=None):
    return _wrap(jnp.mean(_raw(t), axis=axis), getattr(t, "device", None))


def average(t, axis=None):
    return mean(t, axis)


def reshape(t, shape):
    return t.reshape(shape)


def transpose(t, axes=None):
    return t.transpose(axes)


def squeeze(t, axis=None):
    return t.squeeze(axis)


def concatenate(tensors, axis=0):
    dev = tensors[0].device if tensors else None
    return _wrap(jnp.concatenate([_raw(t) for t in tensors], axis=axis), dev)


def stack(tensors, axis=0):
    dev = tensors[0].device if tensors else None
    return _wrap(jnp.stack([_raw(t) for t in tensors], axis=axis), dev)


def repeat(t, repeats, axis=None):
    return _wrap(jnp.repeat(_raw(t), repeats, axis=axis), getattr(t, "device", None))


def tile(t, reps):
    return _wrap(jnp.tile(_raw(t), reps), getattr(t, "device", None))


def add(lhs, rhs, ret=None):
    r = _wrap(_raw(lhs) + _raw(rhs), getattr(lhs, "device", None))
    if ret is not None:
        ret.data = r.data
        return ret
    return r


def sub(lhs, rhs, ret=None):
    r = _wrap(_raw(lhs) - _raw(rhs), getattr(lhs, "device", None))
    if ret is not None:
        ret.data = r.data
        return ret
    return r


def eltwise_mult(lhs, rhs, ret=None):
    r = _wrap(_raw(lhs) * _raw(rhs), getattr(lhs, "device", None))
    if ret is not None:
        ret.data = r.data
        return ret
    return r


def div(lhs, rhs, ret=None):
    r = _wrap(_raw(lhs) / _raw(rhs), getattr(lhs, "device", None))
    if ret is not None:
        ret.data = r.data
        return ret
    return r


def mult(A, B, C=None, alpha=1.0, beta=0.0):
    """GEMM: C = alpha*A@B + beta*C (reference: tensor.cc Mult → cuBLAS
    GEMM in tensor_math_cuda.h; here lax dot_general hits the MXU)."""
    out = alpha * jnp.matmul(_raw(A), _raw(B))
    if C is not None and beta != 0.0:
        out = out + beta * _raw(C)
    r = _wrap(out, getattr(A, "device", None))
    if C is not None:
        C.data = r.data
        return C
    return r


def matmul(A, B):
    return _wrap(jnp.matmul(_raw(A), _raw(B)), getattr(A, "device", None))


def einsum(spec, *tensors):
    dev = getattr(tensors[0], "device", None) if tensors else None
    return _wrap(jnp.einsum(spec, *[_raw(t) for t in tensors]), dev)


def tensordot(A, B, axes=2):
    return _wrap(jnp.tensordot(_raw(A), _raw(B), axes=axes), getattr(A, "device", None))


def axpy(alpha, x, y):
    """y += alpha * x (BLAS axpy; reference tensor_math_cuda.h Axpy)."""
    y.data = y.data + alpha * _raw(x)
    return y


def softmax(t, axis=-1, out=None):
    r = _wrap(jax.nn.softmax(_raw(t), axis=axis), getattr(t, "device", None))
    if out is not None:
        out.data = r.data
        return out
    return r


def lt(t, x):
    return t < x


def le(t, x):
    return t <= x


def gt(t, x):
    return t > x


def ge(t, x):
    return t >= x


def maximum(a, b):
    return _wrap(jnp.maximum(_raw(a), _raw(b)), getattr(a, "device", None))


def minimum(a, b):
    return _wrap(jnp.minimum(_raw(a), _raw(b)), getattr(a, "device", None))


def clip(t, lo, hi):
    return _wrap(jnp.clip(_raw(t), lo, hi), getattr(t, "device", None))


def argmax(t, axis=-1):
    return _wrap(jnp.argmax(_raw(t), axis=axis), getattr(t, "device", None))


def argmin(t, axis=-1):
    return _wrap(jnp.argmin(_raw(t), axis=axis), getattr(t, "device", None))


def where(cond, a, b):
    return _wrap(jnp.where(_raw(cond) != 0, _raw(a), _raw(b)), getattr(a, "device", None))


# -- row/column ops (reference tensor.py add_row/add_column etc. operate on
#    2-D matrices; broadcasting does the work on XLA) ----------------------

def add_column(v, M):
    """M[:, j] += v for all j (v is length-nrows)."""
    M.data = M.data + _raw(v)[:, None]
    return M


def add_row(v, M):
    M.data = M.data + _raw(v)[None, :]
    return M


def mult_column(v, M):
    M.data = M.data * _raw(v)[:, None]
    return M


def mult_row(v, M):
    M.data = M.data * _raw(v)[None, :]
    return M


def div_column(v, M):
    M.data = M.data / _raw(v)[:, None]
    return M


def div_row(v, M):
    M.data = M.data / _raw(v)[None, :]
    return M


def sum_columns(M):
    return _wrap(jnp.sum(_raw(M), axis=1), getattr(M, "device", None))


def sum_rows(M):
    return _wrap(jnp.sum(_raw(M), axis=0), getattr(M, "device", None))


# -- random fills ----------------------------------------------------------

def gaussian(mean, std, t: Tensor):
    return t.gaussian(mean, std)


def uniform(low, high, t: Tensor):
    return t.uniform(low, high)


def bernoulli(p, t: Tensor):
    return t.bernoulli(p)


def zeros_like(t):
    return _wrap(jnp.zeros_like(_raw(t)), getattr(t, "device", None))


def ones_like(t):
    return _wrap(jnp.ones_like(_raw(t)), getattr(t, "device", None))


def zeros(shape, dtype=float32, device=None):
    return Tensor(shape=shape, device=device, dtype=dtype)


def ones(shape, dtype=float32, device=None):
    t = Tensor(shape=shape, device=device, dtype=dtype)
    return t.set_value(1.0)


def eye(n, dtype=float32, device=None):
    return _wrap(jnp.eye(n, dtype=_asdtype(dtype)), device)


def arange(*args, dtype=float32, device=None):
    return _wrap(jnp.arange(*args, dtype=_asdtype(dtype)), device)


def copy_data_to_from(dst: Tensor, src: Tensor, size=None):
    dst.copy_data(src)
    return dst
