"""Optimizers, API-shaped after the reference's ``python/singa/opt.py``
(~900 LoC, unverified — SURVEY.md §2.2): ``Optimizer`` base with decay
scheduling, ``SGD`` (momentum/nesterov/dampening/weight-decay), ``RMSProp``,
``AdaGrad``, ``Adam``, and ``DistOpt`` (defined in this module, implemented
over the ICI communicator in ``parallel/communicator.py``).

TPU-native notes: every piece of optimizer state — momentum buffers, step
counter — is a ``Tensor`` so graph mode (``model.py``) can thread it through
the jitted train step as traced state; the update math is plain jnp and
fuses into the step executable (the reference dispatches one axpy-style
kernel per parameter per update).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import autograd, tensor
from .observe import trace as _trace
from .observe.registry import registry as _obs_registry
from .tensor import Tensor


# ---------------------------------------------------------------------------
# learning-rate / momentum schedulers (reference: opt.DecayScheduler)
# ---------------------------------------------------------------------------

class DecayScheduler:
    def __init__(self, init_value):
        self.init_value = float(init_value)

    def __call__(self, step):
        raise NotImplementedError

    def get_states(self):
        return {"init_value": self.init_value}


class Constant(DecayScheduler):
    def __call__(self, step):
        return jnp.asarray(self.init_value, dtype=jnp.float32)


class ExponentialDecay(DecayScheduler):
    """lr = init * decay_rate ^ (step / decay_steps), optionally staircased
    (reference: opt.ExponentialDecay)."""

    def __init__(self, init_value, decay_steps, decay_rate, staircase=False):
        super().__init__(init_value)
        self.decay_steps = int(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = bool(staircase)

    def __call__(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        p = step / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return jnp.asarray(self.init_value * self.decay_rate**p, dtype=jnp.float32)


class StepDecay(DecayScheduler):
    """lr = init * gamma ^ floor(step / step_size)."""

    def __init__(self, init_value, step_size, gamma=0.1):
        super().__init__(init_value)
        self.step_size = int(step_size)
        self.gamma = float(gamma)

    def __call__(self, step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / self.step_size)
        return jnp.asarray(self.init_value * self.gamma**k, dtype=jnp.float32)


def _as_scheduler(v):
    return v if isinstance(v, DecayScheduler) else Constant(v)


# ---------------------------------------------------------------------------
# Optimizer base
# ---------------------------------------------------------------------------

class Optimizer:
    """Reference contract: ``apply(param_name, param, grad)`` updates one
    parameter in place; ``__call__(loss)`` / ``backward_and_update(loss)``
    consume the ``autograd.backward`` generator; ``step()`` advances the
    schedule."""

    def __init__(self, lr, dtype=tensor.float32, clip_norm=None):
        self.lr = _as_scheduler(lr)
        self.dtype = dtype
        # global-norm gradient clipping (the transformer standard):
        # grads are scaled by min(1, clip_norm/||g||_global) BEFORE the
        # update rule.  Requires materializing the whole gradient set
        # per step (the norm is global), so backward_and_update
        # two-passes when it is set and streams otherwise.  DistOpt's
        # dense/fp16 sync modes clip too — the mirrored pass sits
        # between sync and apply (DistOpt._apply_all), so the clipped
        # quantity is the synced (= full-batch) gradient and the
        # distributed run matches the single-device clipped oracle;
        # the partial/sparse modes refuse clip_norm (no per-step
        # global gradient exists to clip).
        if clip_norm is not None and clip_norm <= 0:
            raise ValueError(f"clip_norm must be > 0, got {clip_norm}")
        self.clip_norm = None if clip_norm is None else float(clip_norm)
        # step counter is a Tensor so lr schedules stay correct inside a
        # compiled graph-mode step
        self.step_counter = Tensor(shape=(), dtype=tensor.float32,
                                   requires_grad=False)
        # observe: resolved once — eager mode runs an update per step,
        # so the hot path pays one integer add, not a registry lookup.
        # Under graph mode the update fuses into the compiled step, so
        # (like comms.*) this counts once per COMPILE, not per replayed
        # step — train.steps is the per-step count there.
        self._m_updates = _obs_registry().counter(
            "opt.updates",
            help="optimizer update passes (eager: per step; graph "
                 "mode: at trace time, once per compile)",
            optimizer=type(self).__name__)
        self._states = {}  # name -> Tensor (momentum buffers etc.)
        self._name_of = {}  # id(param Tensor) -> assigned name

    # -- naming / state ----------------------------------------------------
    def _param_name(self, param) -> str:
        pid = id(param)
        if pid not in self._name_of:
            n = param.name if param.name else f"param_{len(self._name_of)}"
            # ensure uniqueness
            if n in self._name_of.values():
                n = f"{n}_{pid:x}"
            self._name_of[pid] = n
        return self._name_of[pid]

    def _state(self, key, like) -> Tensor:
        if key not in self._states:
            t = Tensor(shape=like.shape, dtype=like.data.dtype,
                       device=like.device, requires_grad=False)
            self._states[key] = t
        t = self._states[key]
        if t.device is not like.device:
            # e.g. restored from checkpoint before params were seen
            t.to_device(like.device)
        return t

    def _step_on(self, param):
        """Step counter placed on the param's device (it is created before
        any param is seen, so its first placement may be wrong)."""
        if self.step_counter.device is not param.device:
            self.step_counter.to_device(param.device)
        return self.step_counter.data

    def state_tensors(self) -> dict:
        """All persistent state (used by graph mode + checkpointing)."""
        d = dict(self._states)
        d["__step_counter__"] = self.step_counter
        return d

    def get_states(self) -> dict:
        return {k: tensor.to_numpy(v) for k, v in self.state_tensors().items()}

    def set_states(self, states: dict):
        import jax

        for k, v in states.items():
            if k == "__step_counter__":
                self.step_counter.data = jax.device_put(
                    jnp.asarray(v), self.step_counter.device.jax_device)
            elif k in self._states:
                t = self._states[k]
                t.data = jax.device_put(jnp.asarray(v), t.device.jax_device)
            else:
                # buffer not materialized yet (momentum is created lazily on
                # first apply); stage it on the default device — _state()
                # is keyed by name, so the staged tensor is picked up and
                # later math follows the param's placement
                self._states[k] = tensor.from_numpy(np.asarray(v))

    # -- gradient clipping -------------------------------------------------
    def _clip_pairs(self, pairs):
        """Scale every grad by min(1, clip_norm/||g||_global).  The
        tiny-eps guard keeps a zero-gradient step finite."""
        sq = sum(jnp.sum(jnp.square(g.data.astype(jnp.float32)))
                 for _, g in pairs)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
        for _, g in pairs:
            g.data = (g.data.astype(jnp.float32)
                      * scale).astype(g.data.dtype)
        return pairs

    # -- the reference API -------------------------------------------------
    def __call__(self, loss):
        self.backward_and_update(loss)

    def _grad_pairs(self, loss):
        """The (param, grad) stream: the raw generator when unclipped
        (apply interleaves with backward as grads finalize), or the
        materialized-and-clipped list when clip_norm is set."""
        if self.clip_norm is None:
            return autograd.backward(loss)
        return self._clip_pairs(list(autograd.backward(loss)))

    def backward_and_update(self, loss):
        # the span measures HOST time — eager dispatch in eager mode,
        # trace construction under graph mode's jit (where the update
        # math fuses into the step and has no separable device cost)
        with _trace.span("opt/update", cat="train",
                         optimizer=type(self).__name__) as sp:
            n = 0
            for p, g in self._grad_pairs(loss):
                self.apply(self._param_name(p), p, g)
                n += 1
            self.step()
            sp.set(params=n)
        self._m_updates.inc()

    def call_with_returns(self, loss):
        pn_p_g = []
        with _trace.span("opt/update", cat="train",
                         optimizer=type(self).__name__) as sp:
            for p, g in self._grad_pairs(loss):
                self.apply(self._param_name(p), p, g)
                pn_p_g.append((self._param_name(p), p, g))
            self.step()
            sp.set(params=len(pn_p_g))
        self._m_updates.inc()
        return pn_p_g

    def step(self):
        self.step_counter.data = self.step_counter.data + 1

    def apply(self, param_name, param, grad):
        raise NotImplementedError

    def update(self, param, grad):
        """Reference alias: update one param given its grad."""
        self.apply(self._param_name(param), param, grad)

    # applying an update rebinds param.data; reset its creator so autograd
    # attaches a fresh Dummy next step
    @staticmethod
    def _assign(param, new_value):
        param.data = new_value.astype(param.data.dtype)
        param.creator = None


class SGD(Optimizer):
    """Reference opt.SGD: momentum, dampening, nesterov, weight decay."""

    def __init__(self, lr=0.1, momentum=0.0, dampening=0.0, weight_decay=0.0,
                 nesterov=False, dtype=tensor.float32, clip_norm=None):
        super().__init__(lr, dtype, clip_norm=clip_norm)
        self.momentum = _as_scheduler(momentum)
        self.dampening = _as_scheduler(dampening)
        self.weight_decay = _as_scheduler(weight_decay)
        self.nesterov = bool(nesterov)
        if nesterov and (momentum == 0 if isinstance(momentum, (int, float)) else False):
            raise ValueError("nesterov requires momentum > 0")

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        mom = self.momentum(step)
        damp = self.dampening(step)
        wd = self.weight_decay(step)
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        g = g + wd * p
        has_momentum = not (isinstance(self.momentum, Constant)
                            and self.momentum.init_value == 0.0)
        if has_momentum:
            buf = self._state(f"{param_name}:momentum", param)
            new_buf = mom * buf.data.astype(jnp.float32) + (1.0 - damp) * g
            buf.data = new_buf
            g = (g + mom * new_buf) if self.nesterov else new_buf
        self._assign(param, p - lr * g)


class RMSProp(Optimizer):
    """Reference opt.RMSProp: running mean of squared grads."""

    def __init__(self, lr=0.1, rho=0.9, epsilon=1e-8, weight_decay=0.0,
                 clip_norm=None):
        super().__init__(lr, clip_norm=clip_norm)
        self.rho = float(rho)
        self.epsilon = float(epsilon)
        self.weight_decay = _as_scheduler(weight_decay)

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        wd = self.weight_decay(step)
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        g = g + wd * p
        v = self._state(f"{param_name}:sq", param)
        v.data = self.rho * v.data.astype(jnp.float32) + (1 - self.rho) * g * g
        self._assign(param, p - lr * g / jnp.sqrt(v.data + self.epsilon))


class AdaGrad(Optimizer):
    def __init__(self, lr=0.1, epsilon=1e-8, weight_decay=0.0,
                 clip_norm=None):
        super().__init__(lr, clip_norm=clip_norm)
        self.epsilon = float(epsilon)
        self.weight_decay = _as_scheduler(weight_decay)

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        wd = self.weight_decay(step)
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        g = g + wd * p
        h = self._state(f"{param_name}:accum", param)
        h.data = h.data.astype(jnp.float32) + g * g
        self._assign(param, p - lr * g / jnp.sqrt(h.data + self.epsilon))


class Adam(Optimizer):
    """Reference opt.Adam with bias correction."""

    def __init__(self, lr=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
                 weight_decay=0.0, clip_norm=None):
        super().__init__(lr, clip_norm=clip_norm)
        self.beta_1 = float(beta_1)
        self.beta_2 = float(beta_2)
        self.epsilon = float(epsilon)
        self.weight_decay = _as_scheduler(weight_decay)

    def _direction(self, param_name, param, g, t):
        """Bias-corrected adaptive direction m̂/(√v̂+ε) — shared by the
        coupled (Adam) and decoupled (AdamW) decay variants so the
        moment math can never diverge between them."""
        m = self._state(f"{param_name}:m", param)
        v = self._state(f"{param_name}:v", param)
        m.data = self.beta_1 * m.data.astype(jnp.float32) + (1 - self.beta_1) * g
        v.data = self.beta_2 * v.data.astype(jnp.float32) + (1 - self.beta_2) * g * g
        m_hat = m.data / (1 - self.beta_1**t)
        v_hat = v.data / (1 - self.beta_2**t)
        return m_hat / (jnp.sqrt(v_hat) + self.epsilon)

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        wd = self.weight_decay(step)
        t = step.astype(jnp.float32) + 1.0
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        g = g + wd * p  # coupled decay rides the gradient
        self._assign(param, p - lr * self._direction(param_name, param,
                                                     g, t))


class AdamW(Adam):
    """Adam with DECOUPLED weight decay (Loshchilov & Hutter): the
    decay term subtracts lr·wd·p directly from the parameter instead
    of riding the gradient through the adaptive denominator (Adam's
    coupled decay shrinks large-|v| coordinates less — the reason
    AdamW generalizes better and is the de-facto transformer
    default).  Beyond the reference's optimizer list (it stops at
    Adam); same states/scheduler machinery."""

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        wd = self.weight_decay(step)
        t = step.astype(jnp.float32) + 1.0
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        self._assign(param, p - lr * (self._direction(param_name, param,
                                                      g, t) + wd * p))


class Lion(Optimizer):
    """Lion (Chen et al., 2023): sign of an interpolated momentum —
    ONE state tensor per parameter (vs Adam's two) and every update
    coordinate has magnitude exactly lr, which makes it robust in
    low precision (the sign survives bf16 where Adam's v underflows).
    Decay is decoupled as in AdamW."""

    def __init__(self, lr=1e-4, beta_1=0.9, beta_2=0.99,
                 weight_decay=0.0, clip_norm=None):
        super().__init__(lr, clip_norm=clip_norm)
        self.beta_1 = float(beta_1)
        self.beta_2 = float(beta_2)
        self.weight_decay = _as_scheduler(weight_decay)

    def apply(self, param_name, param, grad):
        step = self._step_on(param)
        lr = self.lr(step)
        wd = self.weight_decay(step)
        g = grad.data.astype(jnp.float32)
        p = param.data.astype(jnp.float32)
        m = self._state(f"{param_name}:m", param)
        mf = m.data.astype(jnp.float32)
        update = jnp.sign(self.beta_1 * mf + (1 - self.beta_1) * g)
        self._assign(param, p - lr * (update + wd * p))
        m.data = self.beta_2 * mf + (1 - self.beta_2) * g


# DistOpt lives with the communicator; re-exported here to match the
# reference import path `from singa import opt; opt.DistOpt(sgd)`.
def __getattr__(name):
    if name == "DistOpt":
        from .parallel.dist_opt import DistOpt

        return DistOpt
    raise AttributeError(name)
