"""Pipeline parallelism — GPipe microbatch schedule over the ``pipe``
mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3); this is the
TPU-native extension.  Design (the shard_map+ppermute pattern, not a
torch-style stage-process translation):

  * the L identical transformer blocks' parameters are STACKED with a
    leading layer dim sharded ``P(pipe, ...)`` — each chip holds the
    weights of its L/P resident layers and scans over them locally;
  * the global batch splits into M microbatches; activations flow
    stage-to-stage via ``lax.ppermute`` one ICI hop forward per tick,
    M + P - 1 ticks total (bubble fraction (P-1)/(M+P-1));
  * the whole schedule is a ``lax.scan`` inside one ``shard_map`` —
    jax.vjp differentiates it end-to-end, and the reverse pass is
    automatically the reverse pipeline (ppermute's transpose is the
    backward hop);
  * the last stage's outputs are masked-psum'd over ``pipe`` so every
    rank returns the same global result (cheap: activations, not
    params).

Composes with data parallelism (microbatch dim sharded over ``data``);
interleaving tensor parallelism inside a stage is left as the
documented next extension (the block body would use the ``model`` axis
inside this same shard_map).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .. import amp, autograd
from ..layer import Layer
from ..tensor import Tensor
from .sharding import DATA, PIPE, P, ShardingPlan

__all__ = ["gpipe_spmd", "PipelinedTransformer"]


def gpipe_spmd(stage_fn, stage_params, x_mb, axis_name=PIPE):
    """Run the GPipe schedule inside a shard_map.

    stage_fn(local_params, x) -> y        (shape-preserving)
    stage_params: pytree of per-rank arrays (this stage's layers)
    x_mb: (M, mb, ...) microbatched input, identical on every pipe rank
    Returns (M, mb, ...) outputs of the LAST stage, replicated over
    ``axis_name`` via a masked psum.
    """
    world = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    m_count = x_mb.shape[0]
    ticks = m_count + world - 1
    fwd = [(i, i + 1) for i in range(world - 1)]  # no wraparound

    def tick(carry, t):
        buf, outs = carry
        # stage 0 pulls microbatch t (clamped; masked out when t >= M)
        x0 = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m_count - 1), axis=0, keepdims=False)
        x0 = jnp.where(t < m_count, x0, jnp.zeros_like(x0))
        x_in = jnp.where(rank == 0, x0, buf)
        y = stage_fn(stage_params, x_in)
        # one hop forward; rank 0 receives zeros (uses x_mb instead)
        buf_next = lax.ppermute(y, axis_name, fwd) if world > 1 else y
        # last stage emits microbatch m = t - (world - 1)
        m_idx = t - (world - 1)
        emit = jnp.logical_and(rank == world - 1, m_idx >= 0)
        slot = jnp.clip(m_idx, 0, m_count - 1)
        outs = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(emit,
                      y,
                      lax.dynamic_index_in_dim(outs, slot, 0,
                                               keepdims=False)),
            slot, axis=0)
        return (buf_next, outs), None

    zero_buf = jnp.zeros_like(
        lax.dynamic_index_in_dim(x_mb, 0, 0, keepdims=False))
    zero_out = jnp.zeros_like(x_mb)
    (_, outs), _ = lax.scan(tick, (zero_buf, zero_out),
                            jnp.arange(ticks))
    # broadcast the last stage's buffer to every rank
    mask = (rank == world - 1).astype(outs.dtype)
    return lax.psum(outs * mask, axis_name)


def _block_apply(lp, h, num_heads, causal, eps):
    """One pre-LN transformer block in pure jnp over a param dict
    (a single layer's slice of the stacked pipeline params)."""
    mb, s, d = h.shape
    hd = d // num_heads

    def ln(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * g + b

    x = ln(h, lp["ln1_g"], lp["ln1_b"])
    q = (x @ lp["wq"] + lp["bq"]).reshape(mb, s, num_heads, hd)
    k = (x @ lp["wk"] + lp["bk"]).reshape(mb, s, num_heads, hd)
    v = (x @ lp["wv"] + lp["bv"]).reshape(mb, s, num_heads, hd)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    sc = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(hd)
    if causal:
        cm = jnp.tril(jnp.ones((s, s), bool))
        sc = jnp.where(cm[None, None], sc, -1e30)
    p = jax.nn.softmax(sc, axis=-1)
    ctx = jnp.einsum("bhst,bhtd->bhsd", p, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(mb, s, d)
    h = h + ctx @ lp["wo"] + lp["bo"]
    x = ln(h, lp["ln2_g"], lp["ln2_b"])
    f = jax.nn.gelu(x @ lp["w1"] + lp["b1"])
    return h + f @ lp["w2"] + lp["b2"]


_PARAM_ORDER = ("ln1_g", "ln1_b", "wq", "bq", "wk", "bk", "wv", "bv",
                "wo", "bo", "ln2_g", "ln2_b", "w1", "b1", "w2", "b2")


class PipelinedTransformer(Layer):
    """L pre-LN transformer blocks executed as a GPipe pipeline over the
    ``pipe`` mesh axis (plain sequential scan when plan is None or
    pipe=1 — one definition serves single-chip and pipelined runs).

    Parameters are stacked (L, ...) tensors sharded P(pipe, ...); inside
    the shard_map each rank lax.scans over its resident L/P layers.
    """

    def __init__(self, num_layers, num_heads, intermediate,
                 plan: ShardingPlan | None = None, num_microbatches=None,
                 causal=True, eps=1e-5, remat=False):
        super().__init__()
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.intermediate = int(intermediate)
        self.plan = plan
        self.causal = bool(causal)
        self.eps = float(eps)
        # remat: recompute each block in backward (jax.checkpoint per
        # scanned layer) — the standard transformer memory recipe;
        # composes with the pipeline (backward ticks recompute their
        # stage's blocks)
        self.remat = bool(remat)
        pp = 1 if plan is None else plan.axis_size(PIPE)
        if self.num_layers % pp != 0:
            raise ValueError(
                f"num_layers {self.num_layers} not divisible by pipe-axis "
                f"size {pp}")
        self.num_microbatches = (int(num_microbatches)
                                 if num_microbatches else 2 * pp)

    def initialize(self, x):
        d = x.shape[-1]
        f = self.intermediate
        ll = self.num_layers
        dt = amp.param_dtype(x.data.dtype)
        dev = x.device

        def param(shape, std, ones=False):
            t = Tensor((ll,) + shape, device=dev, dtype=dt,
                       requires_grad=True, stores_grad=True)
            if ones:
                t.set_value(1.0)
            elif std > 0:
                t.gaussian(0.0, std)
            t.partition_spec = P(*([PIPE] + [None] * len(shape)))
            return t

        sd = 0.02
        self.ln1_g = param((d,), 0, ones=True)
        self.ln1_b = param((d,), 0)
        self.wq = param((d, d), sd)
        self.bq = param((d,), 0)
        self.wk = param((d, d), sd)
        self.bk = param((d,), 0)
        self.wv = param((d, d), sd)
        self.bv = param((d,), 0)
        self.wo = param((d, d), sd)
        self.bo = param((d,), 0)
        self.ln2_g = param((d,), 0, ones=True)
        self.ln2_b = param((d,), 0)
        self.w1 = param((d, f), sd)
        self.b1 = param((f,), 0)
        self.w2 = param((f, d), sd)
        self.b2 = param((d,), 0)

    def _stage_fn(self):
        nh, causal, eps = self.num_heads, self.causal, self.eps

        def body(lp, h):
            return _block_apply(lp, h, nh, causal, eps)

        if self.remat:
            body = jax.checkpoint(body)

        def stage(local_params, x):
            def one_layer(h, lp):
                return body(lp, h), None

            y, _ = lax.scan(one_layer, x, local_params)
            return y

        return stage

    def forward(self, x):
        from . import sharding as shd

        b, s, d = x.shape
        params = [getattr(self, n) for n in _PARAM_ORDER]
        plan = self.plan
        pipelined = (plan is not None and plan.axis_size(PIPE) > 1
                     and shd.plan_active())
        stage = self._stage_fn()

        if not pipelined:
            def serial(xv, *ps):
                lp = dict(zip(_PARAM_ORDER, ps))
                return stage(lp, xv)

            return autograd._op(serial, x, *params, _name="TransformerStack")

        m_count = self.num_microbatches
        if b % m_count != 0:
            raise ValueError(
                f"batch {b} not divisible by num_microbatches {m_count}")
        mb = b // m_count
        dp = plan.axis_size(DATA)
        if mb % dp != 0:
            raise ValueError(
                f"microbatch {mb} not divisible by data-axis size {dp}")

        pspec = [P(*([PIPE] + [None] * (t.data.ndim - 1))) for t in params]
        xspec = P(None, DATA, None, None)  # (M, mb@data, S, D)

        def run(xv, *ps):
            x_mb = xv.reshape(m_count, mb, s, d)

            def inner(x_mb_, *ps_):
                lp = dict(zip(_PARAM_ORDER, ps_))
                return gpipe_spmd(stage, lp, x_mb_, PIPE)

            y = jax.shard_map(
                inner, mesh=plan.mesh,
                in_specs=(xspec,) + tuple(pspec),
                out_specs=xspec, check_vma=False)(x_mb, *ps)
            return y.reshape(b, s, d)

        return autograd._op(run, x, *params, _name="GPipe")
