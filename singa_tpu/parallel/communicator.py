"""ICI/DCN communicator — the rebuild of the reference's NCCL+MPI backend
(src/io/communicator.cc + include/singa/io/communicator.h, unverified —
SURVEY.md §2.1/§5.8): ``Communicator`` with ``synch`` (all-reduce),
``fusedSynch`` (bucketed), ``synchHalf`` (fp16-compressed), and top-K
``sparsification`` with residual accumulation, NCCL-id bootstrap via MPI.

TPU-native design:
  * control plane: ``jax.distributed.initialize`` (single controller per
    host over DCN) replaces MPI rank discovery / NCCL-id broadcast;
  * data plane: XLA collectives over ICI — ``lax.psum`` / ``all_gather``
    inside a ``shard_map`` over ``Mesh(devices, ('data',))`` replace
    ncclAllReduce on the dedicated comm stream.  Stream/event ordering
    (``Communicator::wait``) disappears: XLA's scheduler interleaves
    collectives with compute (latency hiding), which is what the
    reference's comm-stream + generator-overlap machinery hand-builds.

Collective calls are only legal while tracing inside the mesh context
(graph-mode training step); eager calls raise with guidance, since in a
single-controller runtime per-rank eager execution does not exist.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..observe import trace as _otrace
from ..observe.registry import registry as _obs_registry
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy, retry_call

__all__ = ["Communicator", "get_mesh", "initialize_distributed",
           "is_tracing", "process_info"]

_DEFAULT_AXIS = "data"


def process_info() -> dict:
    """This host's place in the (possibly multi-process) run — the
    identity every ``{process=<index>}``-labeled metric and health
    report uses.  In single-controller single-host runs this is
    ``{0, 1}``; after :func:`initialize_distributed` it reflects the
    coordinated world, so a crash bundle or straggler summary from any
    host names itself unambiguously."""
    return {
        "process_index": int(jax.process_index()),
        "process_count": int(jax.process_count()),
        "local_device_count": int(jax.local_device_count()),
    }


# host-side dispatch-site retry policy for INJECTED comm.collective
# faults (fast backoff — a collective stall is milliseconds, not the
# checkpoint layer's I/O seconds).  Scope is the injection site only:
# real XLA collective execution happens inside compiled steps where
# host-side retry cannot reach; what this buys is chaos-testing the
# retry/backoff/counter plumbing on the comm path end to end.
_COMM_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                          max_delay_s=0.1)


def _record_collective(op, arrs, axis=None, world=None):
    """Observe hook for one collective issue: per-op count + payload
    bytes (registry ``comms.collectives``/``comms.bytes``) and a trace
    instant.  Collectives execute inside compiled steps, so this fires
    at TRACE time — counts are per-compile, not per-replayed-step
    (a replay issues the same collectives XLA baked in).

    ``axis``/``world``: the mesh axis the collective reduces over and
    its size.  They ride the trace event's args so a Chrome trace can
    tell a TP-serve psum over the ``tp`` axis (serve/tp.py, via
    ``gpt2_decode._tp_psum``) from a data-parallel gradient all-reduce
    — previously every collective looked alike in the trace.

    Also the ``comm.collective`` fault-injection site: armed INJECTED
    faults fire here (host side, trace time) and transient ones retry
    under ``_COMM_RETRY`` — ``resilience.retries{site=comm.collective}``
    counts them; disarmed, the hook is one module-flag read and no
    retry machinery runs (real in-step collective errors are XLA's to
    surface, not host-retryable)."""
    if _faults._armed:
        retry_call(lambda: _faults.check("comm.collective"),
                   "comm.collective", policy=_COMM_RETRY)
    n = 0
    for a in arrs:
        try:
            n += int(np.prod(a.shape or (1,))) * a.dtype.itemsize
        except (AttributeError, TypeError):
            pass
    reg = _obs_registry()
    reg.counter("comms.collectives",
                help="collective ops issued (at trace time)",
                op=op).inc()
    reg.counter("comms.bytes",
                help="collective payload bytes (at trace time)",
                op=op).inc(n)
    _otrace.event(f"comms/{op}", cat="comms", bytes=n,
                  arrays=len(arrs), axis=axis,
                  world=world)


def _wait_for_coordinator(address, timeout):
    """Bounded TCP probe of the rank-0 coordinator.  jax's coordination
    client LOG(FATAL)s (process abort, no Python exception) when
    registration times out, so reachability is checked HERE first to
    turn "coordinator never came up" into a clean, catchable error —
    the failure-detection behavior the reference gets from MPI's
    startup handshake (SURVEY.md §5.3/§5.8)."""
    import socket
    import time

    host, _, port = str(address).rpartition(":")
    host = host.strip("[]")  # bracketed IPv6 form "[::1]:1234"
    if not host or not port.isdigit():
        return  # unparseable address: let jax's own validation report it
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, int(port)), timeout=2):
                return
        except OSError:
            time.sleep(0.5)
    raise ConnectionError(
        f"coordinator {address} unreachable after {timeout:.0f}s: check "
        f"that the process_id=0 task is up and the address/port are "
        f"correct")


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None, **kw):
    """Multi-host bootstrap (reference: MPI init + NCCL-id broadcast).

    Accepts jax.distributed.initialize kwargs; ``initialization_timeout``
    (seconds, default 300) also bounds the pre-flight coordinator
    reachability probe on non-zero ranks, which raises ConnectionError
    instead of letting the coordination client abort the process."""
    if coordinator_address and process_id not in (None, 0):
        _wait_for_coordinator(coordinator_address,
                              kw.get("initialization_timeout", 300))
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kw)


def get_mesh(num_devices=None, axis_name=_DEFAULT_AXIS, devices=None):
    """1-D data-parallel mesh over all (or the first N) devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def is_tracing(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# Eager (outside shard_map) semantics: in a single-controller runtime the
# eager path sees the FULL global batch on one device, so the correct
# "collective" is the world-1 identity — an eager DistOpt step is exact
# single-device training, and the parallelism only exists inside the
# compiled (use_graph=True) step.  The same world-1 path serves graph
# mode's abstract eval_shape warm-up probe (model._materialize_state),
# where no mesh axis is bound.


class Communicator:
    """API-parity communicator; every method matching the reference's
    operates on raw jax arrays *inside* the shard_map'd step."""

    def __init__(self, mesh=None, axis_name=_DEFAULT_AXIS, num_devices=None):
        self.axis_name = axis_name
        self.mesh = mesh if mesh is not None else get_mesh(num_devices,
                                                           axis_name)
        self.world_size = int(np.prod([self.mesh.shape[a]
                                       for a in self.mesh.axis_names]))
        # single-controller: this process sees the whole mesh
        self.global_rank = jax.process_index()
        self.local_rank = 0
        self.num_processes = jax.process_count()

    # -- rank info inside the step ----------------------------------------
    def rank_in_step(self):
        try:
            return lax.axis_index(self.axis_name)
        except NameError:
            return 0

    def _in_step(self, arr) -> bool:
        """True when tracing inside the shard_map'd step (axis bound)."""
        if not is_tracing(arr):
            return False
        try:
            lax.axis_index(self.axis_name)
            return True
        except NameError:
            return False

    # -- dense all-reduce (reference: Communicator::synch → ncclAllReduce)
    def all_reduce(self, arr, average=False):
        if not self._in_step(arr):
            return arr  # eager / unsharded: world-1 identity (see above)
        _record_collective("all_reduce", [arr],
                           axis=self.axis_name, world=self.world_size)
        out = lax.psum(arr, self.axis_name)
        return out / self.world_size if average else out

    def synch(self, arr):
        return self.all_reduce(arr, average=False)

    # -- bucketed all-reduce (reference: fusedSynch over a fusion buffer
    #    of `threshold` bytes) --------------------------------------------
    def fused_synch(self, arrs, average=False):
        """Concatenate many small grads, one psum, split back."""
        if not arrs:
            return []
        if not self._in_step(arrs[0]):
            return list(arrs)
        _record_collective("fused_synch", arrs,
                           axis=self.axis_name, world=self.world_size)
        shapes = [a.shape for a in arrs]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        flat = jnp.concatenate([a.reshape(-1) for a in arrs])
        red = lax.psum(flat, self.axis_name)
        if average:
            red = red / self.world_size
        out, off = [], 0
        for s, n in zip(shapes, sizes):
            out.append(red[off:off + n].reshape(s))
            off += n
        return out

    # -- compressed sync (reference: synchHalf, fp16 over the wire;
    #    bf16 is the TPU-native compressed format) ------------------------
    def synch_half(self, arr, average=False):
        if not self._in_step(arr):
            return arr.astype(jnp.bfloat16).astype(arr.dtype)
        _record_collective("synch_half", [arr],
                           axis=self.axis_name, world=self.world_size)
        red = lax.psum(arr.astype(jnp.bfloat16), self.axis_name)
        red = red.astype(arr.dtype)
        return red / self.world_size if average else red

    def fused_synch_half(self, arrs, average=False):
        if not arrs:
            return []
        if not self._in_step(arrs[0]):
            return [a.astype(jnp.bfloat16).astype(a.dtype) for a in arrs]
        _record_collective("fused_synch_half", arrs,
                           axis=self.axis_name, world=self.world_size)
        shapes = [a.shape for a in arrs]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        flat = jnp.concatenate([a.reshape(-1) for a in arrs]).astype(jnp.bfloat16)
        red = lax.psum(flat, self.axis_name).astype(arrs[0].dtype)
        if average:
            red = red / self.world_size
        out, off = [], 0
        for s, n in zip(shapes, sizes):
            out.append(red[off:off + n].reshape(s))
            off += n
        return out

    # -- sparse sync (reference: sparsification/topKSparsification with
    #    residual accumulation).  TPU has no sparse all-reduce primitive
    #    (SURVEY.md §5.8), so two designs are provided:
    #      topK=True : all_gather of (indices, values) pairs — wire cost
    #                  2*K*world, wins when K << size;
    #      topK=False (threshold): dense masked psum — dynamic selection
    #                  counts don't compile to static ICI transfers.
    def sparse_all_reduce(self, arr, residual, spars=0.05, topK=True,
                          average=False):
        """Returns (synced, new_residual); both shaped like arr."""
        in_step = self._in_step(arr)
        if in_step:
            _record_collective(
                "sparse_topk" if topK else "sparse_threshold", [arr],
                axis=self.axis_name, world=self.world_size)
        acc = residual + arr
        flat = acc.reshape(-1)
        n = flat.shape[0]
        if topK:
            k = max(1, int(math.ceil(float(spars) * n)))
            _, idx = lax.top_k(jnp.abs(flat), k)
            vals = flat[idx]
            contrib = jnp.zeros_like(flat).at[idx].set(vals)
            if in_step:
                # exchange the (idx, vals) pairs over ICI
                all_idx = lax.all_gather(idx, self.axis_name)      # (W, k)
                all_vals = lax.all_gather(vals, self.axis_name)    # (W, k)
                summed = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
                    all_vals.reshape(-1))
            else:
                summed = contrib
        else:
            thr = jnp.asarray(spars, dtype=flat.dtype)
            contrib = jnp.where(jnp.abs(flat) > thr, flat, 0.0)
            summed = lax.psum(contrib, self.axis_name) if in_step else contrib
        new_residual = (flat - contrib).reshape(arr.shape)
        if average and in_step:
            summed = summed / self.world_size
        return summed.reshape(arr.shape), new_residual

    # -- ordering (reference: event-sync of comm stream vs compute) -------
    def wait(self):
        """No-op: XLA's dependency graph orders collectives; there is no
        separate comm stream to fence."""
