"""Tensor parallelism — Megatron-style sharded transformer layers.

The reference has NO tensor parallelism (SURVEY.md §2.3: data-parallel
DistOpt is its only modern strategy); this is the TPU-native extension
the survey marks as the ``('data','model')`` mesh-axis design point.

Execution model (see parallel/sharding.py): parameters carry
``PartitionSpec``s over the ``model`` axis; the jitted step runs under
GSPMD, which turns the annotated einsums into local matmuls + the
canonical Megatron collectives —

  * ``ColumnParallelLinear``  W:(in, out/model) — activations leave
    sharded on the feature dim, no communication;
  * ``RowParallelLinear``     W:(in/model, out) — consumes feature-
    sharded activations, XLA inserts the all-reduce (psum over
    ``model``) that closes the pair;
  * attention: heads sharded over ``model`` (column q/k/v + row output
    projection ⇒ exactly one all-reduce per attention block);
  * MLP: column fc1 + row fc2 ⇒ one all-reduce per MLP block;
  * ``VocabParallelEmbedding``: table rows sharded over ``model``; the
    sharded gather lowers to a one-hot matmul + psum on TPU.

Everything also runs UNSHARDED (plan=None or eager mode): the layers
degrade to their serial equivalents, so one model definition serves
single-chip and multi-chip.
"""

from __future__ import annotations

import logging
import math

import jax.numpy as jnp

from .. import amp, autograd, initializer
from ..layer import Layer
from ..tensor import Tensor
from . import sharding
from .sharding import DATA, MODEL, SEQ, P, ShardingPlan, constrain

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelMLP", "ParallelMHA", "ParallelTransformerBlock",
    "decode_param_specs", "decode_cache_spec",
]


# ---------------------------------------------------------------------------
# decode-shaped partition plans (the serve TP backend's layout;
# singa_tpu/serve/tp.py).  The layer classes above shard TRAINING
# tensors via ``partition_spec`` attributes; inference runs on the raw
# pytree ``models/gpt2_decode.extract_params`` extracts, so the same
# Megatron column/row decisions are restated here against that pytree's
# key names.
# ---------------------------------------------------------------------------

#: per-block key -> how its weight shards over the TP axis.  Column
#: weights (q/k/v projections, MLP fc1) split their OUTPUT dim — the
#: per-shard head/column slice needs no communication; row weights
#: (attention out-proj, MLP fc2) split their INPUT dim and close with
#: the block's one psum; everything else (LayerNorms, row biases,
#: embeddings, the LM head) is replicated.
_DECODE_COL_W = ("wq", "wk", "wv", "w1")
_DECODE_COL_B = ("bq", "bk", "bv", "b1")
_DECODE_ROW_W = ("wo", "w2")


#: MoE expert-weight keys: stacked (E, ...) arrays whose LEADING
#: expert axis shards over the serve ``ep`` axis (serve/ep.py); the
#: router ``moe_wg`` stays replicated (tiny, and every rank routes).
_DECODE_EXPERT_W = ("moe_w1", "moe_b1", "moe_w2", "moe_b2")


def decode_param_specs(params, axis=MODEL, ep_axis=None):
    """PartitionSpec pytree (same structure as ``params``) laying an
    ``extract_params`` decode pytree out Megatron-style over ``axis``:
    attention heads + MLP columns partitioned, out-proj/fc2 row-
    partitioned, embeddings/norms/head replicated.  MoE blocks shard
    their stacked expert weights over ``ep_axis`` (the serve
    expert-parallel backend, singa_tpu/serve/ep.py) — without one they
    are rejected here so the failure is a typed construction error
    naming the ``serve(ep=)`` path, not a shape mismatch deep inside a
    shard_map trace."""
    blocks = []
    for li, blk in enumerate(params["blocks"]):
        if "moe_wg" in blk and ep_axis is None:
            raise NotImplementedError(
                f"block {li} is an MoE block: expert weights shard "
                f"over the expert axis, not the tensor-parallel axis "
                f"— serve this model with model.serve(ep=EPConfig("
                f"ep=, tp=)) (singa_tpu/serve/ep.py: expert-parallel "
                f"decode; tp= covers dense/GQA models only)")
        spec = {}
        for k in blk:
            if k in _DECODE_COL_W:
                spec[k] = P(None, axis)
            elif k in _DECODE_COL_B:
                spec[k] = P(axis)
            elif k in _DECODE_ROW_W:
                spec[k] = P(axis, None)
            elif k in _DECODE_EXPERT_W:
                spec[k] = P(ep_axis)
            else:
                spec[k] = P()
        blocks.append(spec)
    out = {k: (None if v is None else P())
           for k, v in params.items() if k != "blocks"}
    out["blocks"] = blocks
    return out


def decode_cache_spec(axis=MODEL):
    """PartitionSpec for every KV-cache pytree leaf the serve engine
    owns — slot arenas ``(L, S, H_kv, W, D)``, paged pools
    ``(L, num_blocks+1, H_kv, B, D)``, cache rows ``(L, 1, H_kv, W,
    D)`` and their trailing-axis-free int8 scales leaves: the KV-HEAD
    axis (always axis 2) shards over ``axis``, everything else stays
    local.  One spec serves every leaf rank because PartitionSpec
    trailing dims default to unsharded."""
    return P(None, None, axis)


class ColumnParallelLinear(Layer):
    """y = x W + b with W's OUTPUT dim sharded over ``model``.

    ``gather_output=False`` (default) leaves y sharded on its last dim —
    feed it to a RowParallelLinear or another column-sharded consumer."""

    def __init__(self, out_features, plan: ShardingPlan | None = None,
                 bias=True, gather_output=False):
        super().__init__()
        self.out_features = int(out_features)
        self.plan = plan
        self.bias = bool(bias)
        self.gather_output = bool(gather_output)

    def initialize(self, x):
        in_features = x.shape[-1]
        dt = amp.param_dtype(x.data.dtype)
        self.W = Tensor((in_features, self.out_features), device=x.device,
                        dtype=dt, requires_grad=True, stores_grad=True)
        initializer.xavier(self.W)
        self.W.partition_spec = P(None, MODEL)
        if self.bias:
            self.b = Tensor((self.out_features,), device=x.device, dtype=dt,
                            requires_grad=True, stores_grad=True)
            self.b.set_value(0.0)
            self.b.partition_spec = P(MODEL)

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        if self.plan is not None:
            spec = self.plan.act_spec(len(y.shape),
                                      model_last=not self.gather_output)
            y = constrain(y, self.plan, spec)
        return y


class RowParallelLinear(Layer):
    """y = x W + b with W's INPUT dim sharded over ``model``; closes a
    column-parallel pair — XLA emits the single psum here."""

    def __init__(self, out_features, plan: ShardingPlan | None = None,
                 bias=True):
        super().__init__()
        self.out_features = int(out_features)
        self.plan = plan
        self.bias = bool(bias)

    def initialize(self, x):
        in_features = x.shape[-1]
        dt = amp.param_dtype(x.data.dtype)
        self.W = Tensor((in_features, self.out_features), device=x.device,
                        dtype=dt, requires_grad=True, stores_grad=True)
        initializer.xavier(self.W)
        self.W.partition_spec = P(MODEL, None)
        if self.bias:
            # bias is applied AFTER the reduction — replicated
            self.b = Tensor((self.out_features,), device=x.device, dtype=dt,
                            requires_grad=True, stores_grad=True)
            self.b.set_value(0.0)

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        if self.plan is not None:
            y = constrain(y, self.plan,
                          self.plan.act_spec(len(y.shape), model_last=False))
        return y


class VocabParallelEmbedding(Layer):
    """Embedding table with vocab rows sharded over ``model``."""

    def __init__(self, vocab_size, embed_dim,
                 plan: ShardingPlan | None = None, std=0.02):
        super().__init__()
        self.vocab_size = int(vocab_size)
        self.embed_dim = int(embed_dim)
        self.plan = plan
        self.std = float(std)

    def initialize(self, ids):
        self.W = Tensor((self.vocab_size, self.embed_dim), device=ids.device,
                        requires_grad=True, stores_grad=True)
        self.W.gaussian(0.0, self.std)
        self.W.partition_spec = P(MODEL, None)

    def forward(self, ids):
        e = autograd.embedding(ids, self.W)
        if self.plan is not None:
            e = constrain(e, self.plan, self.plan.act_spec(len(e.shape)))
        return e


class ParallelMLP(Layer):
    """Transformer FFN: column fc1 → activation → row fc2 (one psum)."""

    def __init__(self, hidden, intermediate, plan: ShardingPlan | None = None,
                 activation="gelu"):
        super().__init__()
        self.fc1 = ColumnParallelLinear(intermediate, plan)
        self.fc2 = RowParallelLinear(hidden, plan)
        self.activation = activation

    def forward(self, x):
        h = self.fc1(x)
        h = getattr(autograd, self.activation)(h)
        return self.fc2(h)


class ParallelMHA(Layer):
    """Multi-head attention with heads sharded over ``model``.

    q/k/v projections are column-parallel (head dim ⊂ feature dim, so the
    per-head split is a local reshape of the sharded feature axis); the
    output projection is row-parallel.  With a real ``seq`` mesh axis and
    ``seq_parallel=True``, the score/value contraction runs as ring
    attention (parallel/ring_attention.py) over the ICI ring — activations
    stay sharded (B@data, H@model, S@seq, D) end to end, so max sequence
    length scales with the seq-axis size (the long-context design the
    reference lacks, SURVEY.md §5.7).

    ``num_kv_heads`` < ``num_heads`` gives grouped-query attention
    (GQA): k/v project to ``num_kv_heads`` heads which each serve a
    contiguous group of ``num_heads // num_kv_heads`` query heads.  In
    training the K/V heads are broadcast up to the full head count
    before the score contraction (the RepeatKV op — GQA's training
    FLOPs match MHA; the win is the num_heads/num_kv_heads× smaller
    K/V cache at inference, where decode is cache-read-bound — see
    models/gpt2_decode.py)."""

    def __init__(self, num_heads, plan: ShardingPlan | None = None,
                 dropout=0.0, seq_parallel=None, causal=False,
                 remat=False, use_flash=False, num_kv_heads=None,
                 window=None):
        super().__init__()
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads or num_heads)
        if self.num_heads % self.num_kv_heads != 0:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {self.num_kv_heads}")
        if window is not None and (not causal or int(window) < 1):
            raise ValueError("window requires causal attention and "
                             f"window >= 1, got {window} "
                             f"(causal={causal})")
        self.window = None if window is None else int(window)
        self.plan = plan
        self.dropout = float(dropout)
        self.causal = bool(causal)
        self.remat = bool(remat)
        self.use_flash = bool(use_flash)
        if seq_parallel is None:
            seq_parallel = plan is not None and plan.axis_size(SEQ) > 1
        self.seq_parallel = bool(seq_parallel)
        self.q_proj = ColumnParallelLinear(0, plan)
        self.k_proj = ColumnParallelLinear(0, plan)
        self.v_proj = ColumnParallelLinear(0, plan)
        self.out_proj = RowParallelLinear(0, plan)
        if plan is not None:
            for what, n in (("num_heads", self.num_heads),
                            ("num_kv_heads", self.num_kv_heads)):
                if n % plan.axis_size(MODEL) != 0:
                    raise ValueError(
                        f"{what} {n} not divisible by model-axis "
                        f"size {plan.axis_size(MODEL)}")

    def initialize(self, x, mask=None):
        e = x.shape[-1]
        if e % self.num_heads != 0:
            raise ValueError(
                f"embed dim {e} not divisible by num_heads {self.num_heads}")
        e_kv = (e // self.num_heads) * self.num_kv_heads
        for proj in (self.q_proj, self.out_proj):
            proj.out_features = e
        for proj in (self.k_proj, self.v_proj):
            proj.out_features = e_kv

    def _heads_spec(self):
        # (B, H, S, D): batch@data, heads@model, seq@seq when ring
        return P(DATA, MODEL, SEQ if self.seq_parallel else None, None)

    def forward(self, x, mask=None):
        b, s, e = x.shape
        h = self.num_heads
        h_kv = self.num_kv_heads
        d = e // h
        plan = self.plan

        def split_heads(t, nh):
            t = autograd.reshape(t, (b, s, nh, d))
            t = autograd.transpose(t, (0, 2, 1, 3))
            if nh != h:  # GQA: broadcast each K/V head over its Q group
                t = autograd.repeat_kv(t, h // nh)
            if plan is not None:
                t = constrain(t, plan, self._heads_spec())
            return t

        q = split_heads(self.q_proj(x), h)
        k = split_heads(self.k_proj(x), h_kv)
        v = split_heads(self.v_proj(x), h_kv)

        if self.seq_parallel and plan is not None \
                and sharding.plan_active():
            if self.window is not None:
                raise NotImplementedError(
                    "sliding-window attention is not implemented on "
                    "the ring sequence-parallel path (a band never "
                    "needs most of the ring's hops — use a plan "
                    "without a seq axis for windowed models, or drop "
                    "window for ring attention)")
            # use_flash composes here: inside shard_map the Pallas
            # kernel runs per device (manual mode), so each ring step's
            # local-Q x visiting-K/V attention is the flash kernel
            ctx = _ring_attention_op(q, k, v, mask, plan, self.causal,
                                     use_flash=self.use_flash)
        else:
            # pallas_call has no GSPMD partitioning rule: under an active
            # sharded plan WITHOUT a seq axis the fused einsum path
            # (auto-partitioned head-locally) is the correct kernel —
            # warn and fall back so an auto-selected attn_impl keeps
            # training (with a seq axis, the branch above runs the
            # flash kernel per ring step inside shard_map)
            use_flash = self.use_flash and not (
                plan is not None and sharding.plan_active())
            if self.use_flash and not use_flash \
                    and not getattr(self, "_warned_flash", False):
                self._warned_flash = True
                logging.getLogger("singa_tpu").warning(
                    "ParallelMHA: use_flash ignored under an active "
                    "ShardingPlan without a seq axis (no GSPMD rule "
                    "for pallas_call outside shard_map); using the "
                    "fused head-sharded path — shard the seq axis to "
                    "get ring attention with per-shard flash kernels")
            ctx = _sdpa(q, k, v, mask, self.causal, remat=self.remat,
                        use_flash=use_flash, window=self.window)
        ctx = autograd.transpose(ctx, (0, 2, 1, 3))
        ctx = autograd.reshape(ctx, (b, s, e))
        if plan is not None:
            ctx = constrain(ctx, plan,
                            plan.act_spec(3, model_last=True))
        if self.dropout > 0:
            ctx = autograd.dropout(ctx, self.dropout)
        return self.out_proj(ctx)


class ParallelTransformerBlock(Layer):
    """Pre-LN transformer block from the parallel pieces: exactly two
    psums over ``model`` per block (attention out-proj + MLP fc2)."""

    def __init__(self, num_heads, intermediate, plan=None, dropout=0.0,
                 causal=False, eps=1e-5, moe_experts=None, moe_top_k=2,
                 moe_capacity_factor=1.25, moe_groups=None, remat=False,
                 use_flash=False, num_kv_heads=None, window=None):
        super().__init__()
        from ..layer import LayerNorm

        self.ln1 = LayerNorm(eps)
        self.attn = ParallelMHA(num_heads, plan, dropout=dropout,
                                causal=causal, remat=remat,
                                use_flash=use_flash,
                                num_kv_heads=num_kv_heads,
                                window=window)
        self.ln2 = LayerNorm(eps)
        self.mlp = None  # needs hidden size; built at initialize
        self._intermediate = int(intermediate)
        self._plan = plan
        self._dropout = float(dropout)
        self._moe = (None if moe_experts is None
                     else (int(moe_experts), int(moe_top_k),
                           float(moe_capacity_factor), moe_groups))
        self._remat = bool(remat)

    def initialize(self, x, mask=None):
        hidden = x.shape[-1]
        if self._moe is not None:
            from .moe import MoEFFN

            e, k, cf, g = self._moe
            self.mlp = MoEFFN(e, self._intermediate, self._plan,
                              top_k=k, capacity_factor=cf, groups=g,
                              remat=self._remat)
        else:
            self.mlp = ParallelMLP(hidden, self._intermediate, self._plan)

    @property
    def aux_loss(self):
        """Taped MoE load-balance loss from the last forward (None for a
        dense block)."""
        return getattr(self.mlp, "last_aux_loss", None)

    def forward(self, x, mask=None):
        a = self.attn(self.ln1(x), mask)
        if self._dropout > 0:
            a = autograd.dropout(a, self._dropout)
        x = autograd.add(x, a)
        m = self.mlp(self.ln2(x))
        if self._dropout > 0:
            m = autograd.dropout(m, self._dropout)
        return autograd.add(x, m)


# ---------------------------------------------------------------------------
# attention kernels (taped)
# ---------------------------------------------------------------------------

def _sdpa(q, k, v, mask, causal, remat=False, use_flash=False,
          window=None):
    """Plain scaled-dot-product attention (B,H,S,D); heads may be sharded
    — the einsums are head-local so GSPMD keeps them collective-free.
    scale/causal/window ride op.params for sonnx's decomposed export;
    remat recomputes the S x S tensors in backward (jax.checkpoint);
    use_flash routes to the Pallas online-softmax kernel, whose HBM
    footprint is O(S·D) instead of O(S²) (the long-context lever —
    see LONGCTX.json for the measured crossover).

    ``window`` (causal only): sliding-window attention — query i sees
    keys in [i-window+1, i] (Mistral-style band).  The band is built
    in-kernel (XLA fuses it into the softmax chain; nothing extra in
    HBM).  The matching decode side keeps an O(window) rolling KV
    cache (models/gpt2_decode.py)."""
    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention_op

        return flash_attention_op(q, k, v, mask, causal=causal,
                                  remat=remat, window=window)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def f(qv, kv, vv, *rest, scale, causal, window):
        sc = jnp.einsum("bhsd,bhtd->bhst", qv, kv) * scale
        if rest:
            sc = sc + rest[0]
        if causal:
            s_, t_ = sc.shape[-2:]
            cm = jnp.tril(jnp.ones((s_, t_), bool))
            if window is not None:
                i = jnp.arange(s_)[:, None]
                j = jnp.arange(t_)[None, :]
                cm = cm & (i - j < window)
            sc = jnp.where(cm[None, None], sc, -1e30)
        p = jnp.exp(sc - sc.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return jnp.einsum("bhst,bhtd->bhsd", p, vv)

    xs = (q, k, v) if mask is None else (q, k, v, mask)
    apply = autograd.checkpoint_op if remat else autograd._op
    return apply(f, *xs, _name="TPAttention", scale=scale,
                 causal=causal, window=window)


def _ring_attention_op(q, k, v, mask, plan, causal, use_flash=False):
    """Ring attention as a taped op: shard_map over the FULL mesh with
    (B@data, H@model, S@seq, D) blocks; the K/V ring rotates over the
    ``seq`` axis only (lax.ppermute — the one collective XLA cannot
    infer).  Differentiable end-to-end (scan+ppermute have exact VJPs).

    ``mask`` (optional): a (B, 1, 1, S) additive key-padding mask; its
    key dim is sequence-sharded and rotates around the ring with K/V.
    Masks with a query dim (full (B,H,S,S) biases) are not expressible
    blockwise here — use seq_parallel=False for those."""
    import jax

    from .ring_attention import (ring_self_attention,
                                 zigzag_repartition,
                                 zigzag_ring_self_attention)

    spec = P(DATA, MODEL, SEQ, None)
    seq_world = plan.axis_size(SEQ)
    s_local = q.shape[2] // seq_world
    if causal and mask is None and seq_world > 1 and s_local % 2 == 0:
        # round 5: causal rings run the load-BALANCED zigzag layout —
        # repartition the contiguous-sharded blocks in (one hop of
        # wire each way), attend balanced, repartition back.  The
        # contiguous causal ring below is kept for odd local lengths
        # and masked/non-causal cases.
        def zz(q_, k_, v_):
            q_ = zigzag_repartition(q_, SEQ)
            k_ = zigzag_repartition(k_, SEQ)
            v_ = zigzag_repartition(v_, SEQ)
            # per-hop checkpointing stays ON (the zigzag callee's
            # default): it is the ring path's O(S_local·D) backward-
            # memory guarantee, deliberately NOT governed by
            # ParallelMHA.remat (which checkpoints the non-seq _sdpa
            # internals) — same contract as the contiguous ring below
            o = zigzag_ring_self_attention(q_, k_, v_, SEQ,
                                           use_flash=use_flash)
            return zigzag_repartition(o, SEQ, inverse=True)

        f = jax.shard_map(zz, mesh=plan.mesh,
                          in_specs=(spec, spec, spec), out_specs=spec,
                          check_vma=False)
        return autograd._op(f, q, k, v, _name="ZigzagRingAttention")
    if mask is not None:
        if mask.shape[-2] != 1:
            raise NotImplementedError(
                "ring attention supports key-padding masks (B,1,1,S); "
                "per-query masks need seq_parallel=False")
        mspec = P(DATA, None, None, SEQ)
        f = jax.shard_map(
            lambda q_, k_, v_, m_: ring_self_attention(
                q_, k_, v_, SEQ, causal=causal, kv_mask=m_,
                use_flash=use_flash),
            mesh=plan.mesh, in_specs=(spec, spec, spec, mspec),
            out_specs=spec, check_vma=False)
        return autograd._op(f, q, k, v, mask, _name="RingAttention")
    f = jax.shard_map(
        lambda q_, k_, v_: ring_self_attention(q_, k_, v_, SEQ,
                                               causal=causal,
                                               use_flash=use_flash),
        mesh=plan.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return autograd._op(f, q, k, v, _name="RingAttention")
