"""``DistOpt`` — data-parallel optimizer wrapper (reference:
``python/singa/opt.py`` DistOpt over the NCCL Communicator, unverified —
SURVEY.md §2.2/§3.3).  All five reference sync modes exist on ICI:

  backward_and_update          dense all-reduce, small grads bucketed
                               into a fusion buffer of ``threshold``
                               elements (reference: fusedSynch)
  backward_and_update_half     compressed sync (fp16 upstream → bf16,
                               the TPU wire format)
  backward_and_partial_update  round-robin: each step only 1/world of the
                               params is synced (true 1/W wire cost — the
                               collective sits inside a lax.cond)
  backward_and_sparse_update   topK=True : top-K of (residual+grad),
                               all_gather'd (idx,val) pairs;
                               topK=False: |value|>threshold masked
                               dense psum; residuals accumulate either way

Per-rank state in a single-controller runtime: the reference lets each
rank keep private residuals (and, in partial update, lets params drift
between syncs).  Here params must stay replicated across the mesh, so
per-rank divergence is held in explicitly *sharded* accumulator state of
shape (world, ...param_shape) — partitioned over the mesh axis by the
graph runner, so each rank reads and writes only its own slice, exactly
like a private NCCL-rank buffer.  For partial update this reinterprets
"params drift, then re-sync" as "grads accumulate per-rank, then the
round-robin sync applies the psum'd accumulator" — same 1/W bandwidth,
gradient-preserving, and well-defined with replicated params.

The wrapper consumes the ``autograd.backward`` generator exactly like the
reference (grads stream out reverse-topologically); under XLA the
compute/communication overlap the reference builds by hand falls out of
the latency-hiding scheduler.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import autograd, tensor
from ..tensor import Tensor
from .communicator import Communicator


class DistOpt:
    is_distributed = True

    def __init__(self, opt, mesh=None, axis_name="data", num_devices=None,
                 communicator=None, **unused_reference_args):
        self.opt = opt
        self.communicator = communicator if communicator is not None else \
            Communicator(mesh=mesh, axis_name=axis_name,
                         num_devices=num_devices)
        self.world_size = self.communicator.world_size
        self.global_rank = self.communicator.global_rank
        self.local_rank = self.communicator.local_rank
        self._residuals = {}  # param name -> residual Tensor (sparse mode)

    # -- delegation so DistOpt quacks like the wrapped Optimizer ----------
    @property
    def step_counter(self):
        return self.opt.step_counter

    def step(self):
        self.opt.step()

    def _param_name(self, p):
        return self.opt._param_name(p)

    def apply(self, name, p, g):
        self.opt.apply(name, p, g)

    def update(self, param, grad):
        """Single-param update with dense all-reduce (reference
        DistOpt.update).  Like the single-device ``Optimizer.update``
        alias, this per-param path does NOT apply ``clip_norm`` — a
        global norm does not exist one parameter at a time; clipping
        lives in the ``backward_and_update``/``_half`` flows (see
        ``_apply_all``), exactly as it lives in
        ``Optimizer.backward_and_update`` on a single device."""
        g = self.communicator.all_reduce(grad.data, average=True)
        self.opt.update(param, tensor._wrap(g, param.device))

    # -- global-norm clipping over SYNCED grads ----------------------------
    def _apply_all(self, triples):
        """Drive the wrapped optimizer over ``(name, param, synced
        grad)`` triples, clipping by GLOBAL norm first when the
        wrapped optimizer carries ``clip_norm`` — the synced-grad
        mirror of ``Optimizer._clip_pairs`` (same eps guard, same
        min(1, c/‖g‖) scale in f32).  Clipping after the mean
        all-reduce is exactly the single-device semantics: the synced
        grad IS the full-batch grad, and params stay replicated, so
        every rank computes the identical scale."""
        clip = getattr(self.opt, "clip_norm", None)
        if clip is not None:
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for _, _, g in triples)
            scale = jnp.minimum(
                1.0, clip / jnp.maximum(jnp.sqrt(sq), 1e-12))
            triples = [(n, p, (g.astype(jnp.float32)
                               * scale).astype(g.dtype))
                       for n, p, g in triples]
        for n, p, g in triples:
            self.opt.apply(n, p, tensor._wrap(g, p.device))

    def state_tensors(self):
        d = dict(self.opt.state_tensors())
        for k, v in self._residuals.items():
            d[f"__residual__{k}"] = v
        return d

    def get_states(self):
        return {k: tensor.to_numpy(v) for k, v in self.state_tensors().items()}

    def set_states(self, states):
        res = {k[len("__residual__"):]: v for k, v in states.items()
               if k.startswith("__residual__")}
        rest = {k: v for k, v in states.items()
                if not k.startswith("__residual__")}
        self.opt.set_states(rest)
        for k, v in res.items():
            if k in self._residuals:
                t = self._residuals[k]
                import jax

                t.data = jax.device_put(jnp.asarray(v), t.device.jax_device)
            else:
                self._residuals[k] = tensor.from_numpy(np.asarray(v))

    def attach_model(self, model):
        self.model = model

    # -- mode 1: dense with fusion buffer ----------------------------------
    def __call__(self, loss):
        self.backward_and_update(loss)

    def backward_and_update(self, loss, threshold=2 ** 21):
        """Dense sync; grads smaller than ``threshold`` elements ride the
        fusion buffer (reference default threshold is elements-based).
        With ``clip_norm`` on the wrapped optimizer, applies are
        deferred until every synced grad exists and the whole set is
        scaled by the global norm (``_apply_all``) — unclipped, grads
        stream straight into apply as before."""
        self._dense_sync(loss, threshold,
                         self.communicator.all_reduce,
                         self.communicator.fused_synch)

    # -- mode 2: compressed ------------------------------------------------
    def backward_and_update_half(self, loss, threshold=2 ** 21):
        """Compressed sync (bf16 wire format); global-norm clipping —
        computed in f32 over the POST-sync grads, so what is clipped
        is exactly what is applied — works here too."""
        self._dense_sync(loss, threshold,
                         self.communicator.synch_half,
                         self.communicator.fused_synch_half)

    def _dense_sync(self, loss, threshold, synch_one, synch_fused):
        clip = getattr(self.opt, "clip_norm", None) is not None
        bucket, pending, deferred = [], [], []
        for p, g in autograd.backward(loss):
            name = self._param_name(p)
            if g.data.size < threshold:
                bucket.append(g.data)
                pending.append((name, p))
                continue
            synced = synch_one(g.data, average=True)
            if clip:
                deferred.append((name, p, synced))
            else:
                self.opt.apply(name, p, tensor._wrap(synced, p.device))
        if bucket:
            for (name, p), synced in zip(
                    pending, synch_fused(bucket, average=True)):
                if clip:
                    deferred.append((name, p, synced))
                else:
                    self.opt.apply(name, p,
                                   tensor._wrap(synced, p.device))
        if clip:
            self._apply_all(deferred)
        self.opt.step()

    # -- mode 3: round-robin partial sync ----------------------------------
    def backward_and_partial_update(self, loss):
        """Round-robin: param i syncs on steps where step ≡ i (mod world);
        off-turn grads accumulate in the per-rank accumulator and are
        folded in at the next sync, so wire cost is 1/world of dense sync
        (the psum executes inside the taken lax.cond branch only)."""
        self._refuse_clip("backward_and_partial_update")
        import jax
        from jax import lax

        comm = self.communicator
        W = self.world_size
        step = self.opt.step_counter.data.astype(jnp.int32)
        for i, (p, g) in enumerate(autograd.backward(loss)):
            name = self._param_name(p)
            r = self._residual_for(name, p)
            r_loc, in_step = self._rank_slice(r, g)
            acc = r_loc + g.data
            if not in_step:
                # eager / warm step: world-1 semantics — always "synced"
                self._write_rank_slice(r, jnp.zeros_like(acc), in_step)
                self.opt.apply(name, p, tensor._wrap(acc, p.device))
                continue
            sync_now = (step % W) == (i % W)

            def do_sync(acc=acc):
                return lax.psum(acc, comm.axis_name) / W, jnp.zeros_like(acc)

            def skip(acc=acc):
                return jnp.zeros_like(acc), acc

            delta, new_res = lax.cond(sync_now, do_sync, skip)
            self._write_rank_slice(r, new_res, in_step)
            self.opt.apply(name, p, tensor._wrap(delta, p.device))
        self.opt.step()

    # -- modes 4/5: sparse with residual accumulation ----------------------
    def backward_and_sparse_update(self, loss, spars=0.05, topK=True):
        self._refuse_clip("backward_and_sparse_update")
        comm = self.communicator
        for p, g in autograd.backward(loss):
            name = self._param_name(p)
            r = self._residual_for(name, p)
            r_loc, in_step = self._rank_slice(r, g)
            synced, new_res = comm.sparse_all_reduce(
                g.data, r_loc, spars=spars, topK=topK, average=True)
            self._write_rank_slice(r, new_res, in_step)
            self.opt.apply(name, p, tensor._wrap(synced, p.device))
        self.opt.step()

    def _refuse_clip(self, mode):
        """Partial/sparse sync modes apply PARTIAL gradient information
        per step (a rank-round-robin slice, or top-K/thresholded
        values with residual carry-over) — there is no per-step global
        gradient whose norm would mean what the single-device
        ``clip_norm`` means, so refusing beats silently clipping the
        wrong thing.  Dense and bf16 sync support clipping (see
        ``_apply_all``)."""
        if getattr(self.opt, "clip_norm", None) is not None:
            raise ValueError(
                f"clip_norm is not supported under DistOpt.{mode} "
                f"(the synced update is a partial gradient; a global "
                f"norm over it is not the single-device clip). Use "
                f"the dense or fp16 sync modes, which clip the synced "
                f"global-norm exactly.")

    def _residual_for(self, name, p) -> Tensor:
        """Per-rank accumulator: global shape (world, *param_shape).  The
        graph runner shards dim 0 over the mesh, giving each rank a
        private slice (the analogue of a per-rank NCCL-side buffer)."""
        if name not in self._residuals:
            self._residuals[name] = Tensor(
                shape=(self.world_size,) + p.shape, dtype=p.data.dtype,
                device=p.device, requires_grad=False)
        t = self._residuals[name]
        if t.device is not p.device:
            t.to_device(p.device)
        return t

    def _rank_slice(self, r, g):
        """Local residual slice + whether we are inside the sharded step.
        Inside the step r.data is the (1, *shape) local shard; eagerly it
        is the full (world, *shape) array (use rank 0's slice)."""
        in_step = self.communicator._in_step(g.data)
        return r.data[0], in_step

    def _write_rank_slice(self, r, new_res, in_step):
        if in_step:
            r.data = new_res[None]
        else:
            # warm/eager step: all rank slices get the same value
            r.data = jnp.broadcast_to(new_res[None],
                                      (self.world_size,) + new_res.shape)
