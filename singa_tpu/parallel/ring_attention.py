"""Ring attention — sequence/context parallelism over the mesh.

The reference has NO long-context machinery (SURVEY.md §5.7: max sequence
length is bounded by single-device memory).  This module is the TPU-native
extension point the survey calls for: shard the sequence axis over a mesh
('seq') axis, keep Q resident per chip, and rotate K/V blocks around the
ICI ring with ``lax.ppermute`` while per-step partial attentions merge by
logsumexp — peak memory per chip is O(S_local · D) and the K/V transfers
overlap with the per-block attention compute (XLA's latency-hiding
scheduler pipelines the permute with the einsum/kernel).

Use ``ring_self_attention`` inside an existing ``shard_map`` (arrays are
per-rank blocks), or ``ring_attention_sharded`` to run over global arrays
on a mesh directly.  Differentiable end-to-end (scan + ppermute have
exact VJPs), so it serves training, not just inference.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def ring_self_attention(q, k, v, axis_name, causal=False, kv_mask=None,
                        remat=True, use_flash=False):
    """Per-rank blocks inside shard_map: q,k,v (B, H, S_local, D).
    Returns (B, H, S_local, D) — the attention of local queries against
    the FULL (globally sharded) key/value sequence.

    One ``lax.scan`` body serves both per-step attention kernels; each
    step produces a NORMALIZED partial ``(o_t, lse_t)`` for the visiting
    K/V shard and the shared merge combines them exactly:
    ``m=max(lse_t)``, ``o = Σ o_t·e^{lse_t−m} / Σ e^{lse_t−m}``.

    ``kv_mask``: optional additive mask over KEY positions, shaped
    (B, 1, 1, S_local) per rank (the sequence-sharded slice of a padding
    mask like BERT's (B,1,1,S) -1e9 mask).  It rotates around the ring
    with its K/V block, so every query applies the right slice.

    ``remat`` (default on): checkpoint each ring step so the scan's VJP
    recomputes the (S_local, S_local) score/prob tiles instead of saving
    one pair per hop — backward memory drops from O(S_local·S) to
    O(S_local·D) per rank, the same cure the single-chip Pallas flash
    backward applies (ops/pallas/flash_attention.py), for ~⅓ more
    backward FLOPs.

    ``use_flash``: each ring step's (local Q) × (visiting K/V shard)
    attention runs through the Pallas flash kernel instead of the fused
    einsum — inside shard_map the kernel executes per device (manual
    mode), so this composes the single-chip flash win with sequence
    parallelism.  Causal steps specialize per block position (above the
    diagonal: skipped entirely; on it: causal kernel; below: dense
    kernel).  ``remat`` is ignored here because the kernel's custom VJP
    already recomputes probabilities blockwise from the saved logsumexp
    — and since the round-4 pad-to-block wrapper, flash_attention_lse
    takes the kernel path at EVERY (S_local, D), so the O(S_local·D)
    backward-memory guarantee holds unconditionally (the old jnp
    fallback that betrayed it on unaligned shapes is gone)."""
    axis_size = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    q_pos = rank * s_loc + jnp.arange(s_loc)  # global positions (S_local,)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention_lse

        def step_attn(src, k_cur, v_cur, mask_cur):
            def dense(_):
                o, lse = flash_attention_lse(q, k_cur, v_cur, mask_cur,
                                             causal=False)
                return o.astype(jnp.float32), lse

            def diag(_):
                o, lse = flash_attention_lse(q, k_cur, v_cur, mask_cur,
                                             causal=True)
                return o.astype(jnp.float32), lse

            def skip(_):
                return (jnp.zeros((b, h, s_loc, d), jnp.float32),
                        jnp.full((b, h, s_loc), NEG_INF, jnp.float32))

            if not causal:
                return dense(None)
            return lax.cond(
                src > rank, skip,
                lambda op: lax.cond(src == rank, diag, dense, op), None)

        wrap_remat = False  # the kernel backward already recomputes
    else:
        scale = 1.0 / math.sqrt(d)
        qs = q * scale

        def step_attn(src, k_cur, v_cur, mask_cur):
            sc = jnp.einsum("bhsd,bhtd->bhst", qs, k_cur)
            if mask_cur is not None:
                sc = sc + mask_cur
            if causal:
                k_pos = src * s_loc + jnp.arange(s_loc)
                vis = q_pos[:, None] >= k_pos[None, :]
                sc = jnp.where(vis[None, None], sc, NEG_INF)
            # clamp: a -inf-masked full row would give m_c = -inf and
            # p = exp(-inf - -inf) = NaN; with the floor the row yields
            # p = 0, lse_t = NEG_INF and drops out of the merge
            m_c = jnp.maximum(jnp.max(sc, axis=-1), NEG_INF)
            p = jnp.exp(sc - m_c[..., None])
            l_c = jnp.sum(p, axis=-1)
            l_safe = jnp.where(l_c == 0.0, 1.0, l_c)
            o_t = jnp.einsum("bhst,bhtd->bhsd", p,
                             v_cur) / l_safe[..., None]
            return o_t, m_c + jnp.log(l_safe)

        wrap_remat = remat

    def body(carry, t):
        acc, m_prev, l_prev, k_cur, v_cur, mask_cur = carry
        # the K/V block currently held arrived from rank (rank - t) mod W
        src = (rank - t) % axis_size
        o_t, lse_t = step_attn(src, k_cur, v_cur, mask_cur)
        # exact partial merge via per-step logsumexp
        m_new = jnp.maximum(m_prev, lse_t)
        alpha = jnp.exp(m_prev - m_new)
        w = jnp.exp(lse_t - m_new)
        acc = acc * alpha[..., None] + o_t * w[..., None]
        l_new = l_prev * alpha + w
        # rotate K/V (and the key mask) one hop around the ICI ring
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = (None if mask_cur is None
                     else lax.ppermute(mask_cur, axis_name, perm))
        return (acc, m_new, l_new, k_next, v_next, mask_next), None

    if wrap_remat:
        body = jax.checkpoint(body)
    init = (jnp.zeros((b, h, s_loc, d), jnp.float32),
            jnp.full((b, h, s_loc), NEG_INF, jnp.float32),
            jnp.zeros((b, h, s_loc), jnp.float32),
            k, v, kv_mask)
    (acc, m, l, *_), _ = lax.scan(body, init, jnp.arange(axis_size))
    # fully-masked rows (l == 0) normalize to 0, not NaN
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


def zigzag_order(seqlen, world):
    """Permutation putting a global sequence into ZIGZAG layout: the
    sequence splits into 2W half-stripes; rank r holds half-stripes
    [r, 2W-1-r] concatenated.  Returns the gather index array such
    that ``x[..., order, ...]`` lays the sequence out rank-contiguously
    for a P(axis) sharding."""
    if seqlen % (2 * world):
        raise ValueError(f"seqlen {seqlen} not divisible by 2*W={2*world}")
    h = seqlen // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * h, (r + 1) * h))
        idx.extend(range((2 * world - 1 - r) * h, (2 * world - r) * h))
    import numpy as np

    return np.asarray(idx, np.int32)


def zigzag_repartition(x, axis_name, inverse=False):
    """Convert CONTIGUOUS-sharded per-rank sequence blocks (B, H, 2h,
    ...) into the ZIGZAG layout (or back) inside shard_map: rank s's
    halves are the global half-stripes (2s, 2s+1); zigzag rank r wants
    (r, 2W−1−r).  Four PARTIAL ppermutes move every half exactly once
    (non-receiving slots contribute zeros, so the pairwise sums
    reassemble each slot) — total wire per direction = one ring hop's
    K-block, which the balanced causal ring amortizes after a single
    hop's saved compute.  This is what lets the TRAINING stack
    (ParallelMHA) run the balanced layout on contiguous-sharded
    activations without relaying out the whole model."""
    world = lax.psum(1, axis_name)
    s2 = x.shape[2]
    if s2 % 2:
        raise ValueError(f"zigzag repartition needs an even local "
                         f"sequence length, got {s2}")
    h = s2 // 2
    xa, xb = x[:, :, :h], x[:, :, h:]
    pa_low = [(s, 2 * s) for s in range(world) if 2 * s < world]
    pa_high = [(s, 2 * world - 1 - 2 * s) for s in range(world)
               if 2 * s >= world]
    pb_low = [(s, 2 * s + 1) for s in range(world) if 2 * s + 1 < world]
    pb_high = [(s, 2 * world - 2 - 2 * s) for s in range(world)
               if 2 * s + 1 >= world]
    if not inverse:
        low = lax.ppermute(xa, axis_name, pa_low) \
            + lax.ppermute(xb, axis_name, pb_low)
        high = lax.ppermute(xa, axis_name, pa_high) \
            + lax.ppermute(xb, axis_name, pb_high)
        return jnp.concatenate([low, high], axis=2)

    def inv(p):
        return [(d, s) for s, d in p]

    a = lax.ppermute(xa, axis_name, inv(pa_low)) \
        + lax.ppermute(xb, axis_name, inv(pa_high))
    b = lax.ppermute(xa, axis_name, inv(pb_low)) \
        + lax.ppermute(xb, axis_name, inv(pb_high))
    return jnp.concatenate([a, b], axis=2)


def zigzag_ring_self_attention(q, k, v, axis_name, remat=True,
                               use_flash=False):
    """CAUSAL ring attention with the load-balanced ZIGZAG layout
    (round-5 verdict item 4).

    The contiguous layout's causal skip (``ring_self_attention``
    ``causal=True``) leaves rank i computing i+1 block-pairs per pass —
    the last rank does W× the first's work, so the mesh's wall-clock is
    the DENSE cost while half the chips idle.  Here each rank holds two
    half-stripes of the sequence — stripe r and the mirrored stripe
    2W−1−r — so every hop costs every rank exactly two dense
    (S_local/2)² half-attentions:

      * K/V from an earlier rank (src < rank): both the low and high
        local query halves attend ONLY the visiting low half
        (the visiting high half is entirely in their future) —
        one dense (2h × h) attention;
      * K/V from a later rank (src > rank): only the local high half
        attends, but sees BOTH visiting halves — one dense (h × 2h);
      * the diagonal hop (src == rank, once per pass) applies the exact
        global-position causal mask over the full (2h × 2h) tile.

    Per-rank cost is uniform at 2(W−1)+4 dense half-pairs per pass
    (``ring_causal_half_pairs_per_rank`` is the analytic check), vs the
    contiguous layout's 4(i+1) for rank i — total FLOPs match the
    causal optimum within the diagonal tile's masked half.

    Inputs are per-rank blocks inside ``shard_map``, (B, H, 2h, D) in
    zigzag order (``zigzag_order`` produces the global permutation;
    ``zigzag_ring_attention_sharded`` wraps all of it).  Causal only —
    for non-causal use ``ring_self_attention``, where balance is free.
    Differentiable (scan + cond + ppermute have exact VJPs); ``remat``
    checkpoints each hop like the contiguous path.

    ``use_flash``: every half-pair runs through the Pallas flash kernel
    as a SQUARE (h × h) call — the before/after/diagonal branches
    decompose into 2–3 square sub-attentions (dense or causal) whose
    normalized partials merge by logsumexp, so no rectangular or
    general-mask kernel shapes are needed and the O(h·D) backward
    memory guarantee composes with the balanced layout.  ``remat`` is
    ignored there (the kernel's VJP already recomputes blockwise)."""
    axis_size = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    b, nh, s2, d = q.shape
    if s2 % 2:
        raise ValueError(f"zigzag blocks need an even local length, "
                         f"got {s2}")
    h = s2 // 2
    scale = 1.0 / math.sqrt(d)
    # global positions of the local query halves (stripe r, stripe
    # 2W-1-r) — also the visiting K/V's positions on the diagonal hop
    q_pos = jnp.concatenate([
        rank * h + jnp.arange(h),
        (2 * axis_size - 1 - rank) * h + jnp.arange(h)])
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def part(q_, k_, v_, mask):
        """Normalized partial attention (o_t f32, lse_t) over k_/v_."""
        sc = jnp.einsum("bhsd,bhtd->bhst", q_ * scale, k_)
        if mask is not None:
            sc = jnp.where(mask, sc, NEG_INF)
        m_c = jnp.maximum(jnp.max(sc, axis=-1), NEG_INF)
        p = jnp.exp(sc - m_c[..., None])
        l_c = jnp.sum(p, axis=-1)
        l_safe = jnp.where(l_c == 0.0, 1.0, l_c)
        o_t = jnp.einsum("bhst,bhtd->bhsd", p, v_) / l_safe[..., None]
        return o_t.astype(jnp.float32), (m_c + jnp.log(l_safe)).astype(
            jnp.float32)

    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention_lse

        def fpart(q_, k_, v_, causal_):
            o, lse = flash_attention_lse(q_, k_, v_, causal=causal_)
            return o.astype(jnp.float32), lse

        def merge2(o1, l1, o2, l2):
            """Exact merge of two normalized partials (same q rows)."""
            m = jnp.maximum(l1, l2)
            w1 = jnp.exp(l1 - m)
            w2 = jnp.exp(l2 - m)
            den = w1 + w2
            o = (o1 * w1[..., None] + o2 * w2[..., None]) / den[..., None]
            return o, m + jnp.log(den)

    def body(carry, t):
        acc, m_prev, l_prev, k_cur, v_cur = carry
        src = (rank - t) % axis_size

        def before(_):
            # src < rank: every local query is after ALL of the visiting
            # low half and before all of its high half
            if use_flash:
                o1, l1 = fpart(q[:, :, :h], k_cur[:, :, :h],
                               v_cur[:, :, :h], False)
                o2, l2 = fpart(q[:, :, h:], k_cur[:, :, :h],
                               v_cur[:, :, :h], False)
                return (jnp.concatenate([o1, o2], axis=2),
                        jnp.concatenate([l1, l2], axis=2))
            return part(q, k_cur[:, :, :h], v_cur[:, :, :h], None)

        def after(_):
            # src > rank: only the local high half attends; it is after
            # BOTH visiting halves
            if use_flash:
                o1, l1 = fpart(q[:, :, h:], k_cur[:, :, :h],
                               v_cur[:, :, :h], False)
                o2, l2 = fpart(q[:, :, h:], k_cur[:, :, h:],
                               v_cur[:, :, h:], False)
                o_h, lse_h = merge2(o1, l1, o2, l2)
            else:
                o_h, lse_h = part(q[:, :, h:], k_cur, v_cur, None)
            return (jnp.concatenate(
                [jnp.zeros((b, nh, h, d), jnp.float32), o_h], axis=2),
                jnp.concatenate(
                    [jnp.full((b, nh, h), NEG_INF, jnp.float32), lse_h],
                    axis=2))

        def diag(_):
            # src == rank: the low half is plain causal; the high half
            # sees all of the low stripe (dense) + itself (causal)
            if use_flash:
                o_lo, l_lo = fpart(q[:, :, :h], k_cur[:, :, :h],
                                   v_cur[:, :, :h], True)
                o1, l1 = fpart(q[:, :, h:], k_cur[:, :, :h],
                               v_cur[:, :, :h], False)
                o2, l2 = fpart(q[:, :, h:], k_cur[:, :, h:],
                               v_cur[:, :, h:], True)
                o_hi, l_hi = merge2(o1, l1, o2, l2)
                return (jnp.concatenate([o_lo, o_hi], axis=2),
                        jnp.concatenate([l_lo, l_hi], axis=2))
            mask = (q_pos[:, None] >= q_pos[None, :])[None, None]
            return part(q, k_cur, v_cur, mask)

        o_t, lse_t = lax.cond(
            src < rank, before,
            lambda op: lax.cond(src == rank, diag, after, op), None)
        m_new = jnp.maximum(m_prev, lse_t)
        alpha = jnp.exp(m_prev - m_new)
        w = jnp.exp(lse_t - m_new)
        acc = acc * alpha[..., None] + o_t * w[..., None]
        l_new = l_prev * alpha + w
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_new, l_new, k_next, v_next), None

    if remat and not use_flash:  # the kernel VJP already recomputes
        body = jax.checkpoint(body)
    init = (jnp.zeros((b, nh, s2, d), jnp.float32),
            jnp.full((b, nh, s2), NEG_INF, jnp.float32),
            jnp.zeros((b, nh, s2), jnp.float32),
            k, v)
    (acc, m, l, *_), _ = lax.scan(body, init, jnp.arange(axis_size))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


def ring_causal_half_pairs_per_rank(world, layout="zigzag"):
    """Analytic per-rank work for one causal ring pass, in dense
    (S_local/2)² half-pair units — the balance check the zigzag layout
    exists for.  ``zigzag``: every rank computes 2 per off-diagonal hop
    + 4 on its diagonal hop (half masked) → uniform.  ``contiguous``:
    rank i computes 4·(i+1) (its causal skip drops hops above the
    diagonal; each surviving hop is a full 4-half-pair tile)."""
    if layout == "zigzag":
        return [2 * (world - 1) + 4] * world
    if layout == "contiguous":
        return [4 * (i + 1) for i in range(world)]
    raise ValueError(f"unknown layout {layout!r}")


def zigzag_ring_attention_sharded(q, k, v, mesh=None, axis_name="seq",
                                  use_flash=False):
    """Causal zigzag ring attention over GLOBAL (B, H, S, D) arrays:
    permutes the sequence into zigzag order, shard_maps the balanced
    ring, and permutes back.  The permutation costs one gather each
    way — callers keeping activations in zigzag layout end-to-end
    (the idiomatic long-context training loop) skip both."""
    import numpy as np

    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), (axis_name,))
    world = mesh.shape[axis_name]
    order = zigzag_order(q.shape[2], world)
    inv = np.empty_like(order)
    inv[order] = np.arange(len(order), dtype=np.int32)
    spec = P(None, None, axis_name, None)

    f = jax.shard_map(
        lambda q_, k_, v_: zigzag_ring_self_attention(
            q_, k_, v_, axis_name, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = f(q[:, :, order], k[:, :, order], v[:, :, order])
    return out[:, :, inv]


def ring_attention_sharded(q, k, v, mesh=None, axis_name="seq",
                           causal=False):
    """Global arrays (B, H, S, D) with S sharded over ``axis_name``."""
    if mesh is None:
        import numpy as np

        mesh = Mesh(np.asarray(jax.devices()), (axis_name,))
    spec = P(None, None, axis_name, None)

    f = jax.shard_map(
        lambda q_, k_, v_: ring_self_attention(q_, k_, v_, axis_name,
                                               causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
