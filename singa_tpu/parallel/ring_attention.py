"""Ring attention — sequence/context parallelism over the mesh.

The reference has NO long-context machinery (SURVEY.md §5.7: max sequence
length is bounded by single-device memory).  This module is the TPU-native
extension point the survey calls for: shard the sequence axis over a mesh
('seq') axis, keep Q resident per chip, and rotate K/V blocks around the
ICI ring with ``lax.ppermute`` while per-step partial attentions merge by
logsumexp — peak memory per chip is O(S_local · D) and the K/V transfers
overlap with the per-block attention compute (XLA's latency-hiding
scheduler pipelines the permute with the einsum/kernel).

Use ``ring_self_attention`` inside an existing ``shard_map`` (arrays are
per-rank blocks), or ``ring_attention_sharded`` to run over global arrays
on a mesh directly.  Differentiable end-to-end (scan + ppermute have
exact VJPs), so it serves training, not just inference.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def ring_self_attention(q, k, v, axis_name, causal=False, kv_mask=None,
                        remat=True, use_flash=False):
    """Per-rank blocks inside shard_map: q,k,v (B, H, S_local, D).
    Returns (B, H, S_local, D) — the attention of local queries against
    the FULL (globally sharded) key/value sequence.

    One ``lax.scan`` body serves both per-step attention kernels; each
    step produces a NORMALIZED partial ``(o_t, lse_t)`` for the visiting
    K/V shard and the shared merge combines them exactly:
    ``m=max(lse_t)``, ``o = Σ o_t·e^{lse_t−m} / Σ e^{lse_t−m}``.

    ``kv_mask``: optional additive mask over KEY positions, shaped
    (B, 1, 1, S_local) per rank (the sequence-sharded slice of a padding
    mask like BERT's (B,1,1,S) -1e9 mask).  It rotates around the ring
    with its K/V block, so every query applies the right slice.

    ``remat`` (default on): checkpoint each ring step so the scan's VJP
    recomputes the (S_local, S_local) score/prob tiles instead of saving
    one pair per hop — backward memory drops from O(S_local·S) to
    O(S_local·D) per rank, the same cure the single-chip Pallas flash
    backward applies (ops/pallas/flash_attention.py), for ~⅓ more
    backward FLOPs.

    ``use_flash``: each ring step's (local Q) × (visiting K/V shard)
    attention runs through the Pallas flash kernel instead of the fused
    einsum — inside shard_map the kernel executes per device (manual
    mode), so this composes the single-chip flash win with sequence
    parallelism.  Causal steps specialize per block position (above the
    diagonal: skipped entirely; on it: causal kernel; below: dense
    kernel).  ``remat`` is ignored here because the kernel's custom VJP
    already recomputes probabilities blockwise from the saved logsumexp
    — and since the round-4 pad-to-block wrapper, flash_attention_lse
    takes the kernel path at EVERY (S_local, D), so the O(S_local·D)
    backward-memory guarantee holds unconditionally (the old jnp
    fallback that betrayed it on unaligned shapes is gone)."""
    axis_size = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    q_pos = rank * s_loc + jnp.arange(s_loc)  # global positions (S_local,)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention_lse

        def step_attn(src, k_cur, v_cur, mask_cur):
            def dense(_):
                o, lse = flash_attention_lse(q, k_cur, v_cur, mask_cur,
                                             causal=False)
                return o.astype(jnp.float32), lse

            def diag(_):
                o, lse = flash_attention_lse(q, k_cur, v_cur, mask_cur,
                                             causal=True)
                return o.astype(jnp.float32), lse

            def skip(_):
                return (jnp.zeros((b, h, s_loc, d), jnp.float32),
                        jnp.full((b, h, s_loc), NEG_INF, jnp.float32))

            if not causal:
                return dense(None)
            return lax.cond(
                src > rank, skip,
                lambda op: lax.cond(src == rank, diag, dense, op), None)

        wrap_remat = False  # the kernel backward already recomputes
    else:
        scale = 1.0 / math.sqrt(d)
        qs = q * scale

        def step_attn(src, k_cur, v_cur, mask_cur):
            sc = jnp.einsum("bhsd,bhtd->bhst", qs, k_cur)
            if mask_cur is not None:
                sc = sc + mask_cur
            if causal:
                k_pos = src * s_loc + jnp.arange(s_loc)
                vis = q_pos[:, None] >= k_pos[None, :]
                sc = jnp.where(vis[None, None], sc, NEG_INF)
            # clamp: a -inf-masked full row would give m_c = -inf and
            # p = exp(-inf - -inf) = NaN; with the floor the row yields
            # p = 0, lse_t = NEG_INF and drops out of the merge
            m_c = jnp.maximum(jnp.max(sc, axis=-1), NEG_INF)
            p = jnp.exp(sc - m_c[..., None])
            l_c = jnp.sum(p, axis=-1)
            l_safe = jnp.where(l_c == 0.0, 1.0, l_c)
            o_t = jnp.einsum("bhst,bhtd->bhsd", p,
                             v_cur) / l_safe[..., None]
            return o_t, m_c + jnp.log(l_safe)

        wrap_remat = remat

    def body(carry, t):
        acc, m_prev, l_prev, k_cur, v_cur, mask_cur = carry
        # the K/V block currently held arrived from rank (rank - t) mod W
        src = (rank - t) % axis_size
        o_t, lse_t = step_attn(src, k_cur, v_cur, mask_cur)
        # exact partial merge via per-step logsumexp
        m_new = jnp.maximum(m_prev, lse_t)
        alpha = jnp.exp(m_prev - m_new)
        w = jnp.exp(lse_t - m_new)
        acc = acc * alpha[..., None] + o_t * w[..., None]
        l_new = l_prev * alpha + w
        # rotate K/V (and the key mask) one hop around the ICI ring
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = (None if mask_cur is None
                     else lax.ppermute(mask_cur, axis_name, perm))
        return (acc, m_new, l_new, k_next, v_next, mask_next), None

    if wrap_remat:
        body = jax.checkpoint(body)
    init = (jnp.zeros((b, h, s_loc, d), jnp.float32),
            jnp.full((b, h, s_loc), NEG_INF, jnp.float32),
            jnp.zeros((b, h, s_loc), jnp.float32),
            k, v, kv_mask)
    (acc, m, l, *_), _ = lax.scan(body, init, jnp.arange(axis_size))
    # fully-masked rows (l == 0) normalize to 0, not NaN
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh=None, axis_name="seq",
                           causal=False):
    """Global arrays (B, H, S, D) with S sharded over ``axis_name``."""
    if mesh is None:
        import numpy as np

        mesh = Mesh(np.asarray(jax.devices()), (axis_name,))
    spec = P(None, None, axis_name, None)

    f = jax.shard_map(
        lambda q_, k_, v_: ring_self_attention(q_, k_, v_, axis_name,
                                               causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
