"""Multi-axis device mesh + sharding plan — the model-parallel layer of the
framework.

The reference (apache/singa, SURVEY.md §2.3) ships exactly one parallelism
strategy: synchronous data parallelism over NCCL (``opt.DistOpt``), rebuilt
here as the shard_map path in ``model._GraphRunner``.  This module is the
TPU-native generalization the survey leaves as the designed extension
point: a named ``jax.sharding.Mesh`` over up to five axes —

  * ``data``   — batch (data parallelism; grads psum'd by XLA)
  * ``model``  — tensor parallelism (Megatron-style column/row sharding,
                 see parallel/tensor_parallel.py)
  * ``seq``    — sequence/context parallelism (ring attention over ICI,
                 parallel/ring_attention.py)
  * ``pipe``   — pipeline parallelism (GPipe microbatching over ppermute)
  * ``expert`` — expert parallelism (MoE all-to-all dispatch)

— plus a ``ShardingPlan`` that maps every persistent state tensor and
batch input to a ``PartitionSpec``.  The execution model is GSPMD: the
training step is jitted ONCE over globally-shaped arrays whose shardings
are set by ``device_put`` + in-graph ``with_sharding_constraint``; XLA's
SPMD partitioner inserts the all-reduce / all-gather / reduce-scatter /
all-to-all collectives over ICI.  This is deliberately NOT a translation
of the reference's NCCL calls: explicit collectives appear only where the
partitioner cannot infer them (ring attention's ppermute, the pipeline's
stage rotation).

Composes with the tape autograd: parameters carry a ``partition_spec``
attribute; activations are constrained through ``constrain()``, a taped
op (identity in eager mode, ``lax.with_sharding_constraint`` while the
graph-mode step is being traced with a plan active).
"""

from __future__ import annotations

import math
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import autograd

__all__ = [
    "DATA", "MODEL", "SEQ", "PIPE", "EXPERT", "TP", "EP", "PP", "AXES",
    "create_mesh", "create_tp_mesh", "create_ep_mesh", "create_pp_mesh",
    "ShardingPlan", "constrain", "plan_active",
]

DATA = "data"
MODEL = "model"
SEQ = "seq"
PIPE = "pipe"
EXPERT = "expert"
AXES = (DATA, MODEL, SEQ, PIPE, EXPERT)

#: the SERVE-side tensor-parallel axis (singa_tpu/serve/tp.py): a
#: standalone 1-D mesh over which one inference engine's weights and
#: paged KV arena shard.  Deliberately NOT one of the training AXES —
#: a serve process owns its decode mesh outright, and keeping the name
#: distinct means a Chrome trace can tell a TP-serve psum from a
#: training ``model``-axis collective at a glance.
TP = "tp"

#: the SERVE-side expert-parallel axis (singa_tpu/serve/ep.py): the
#: leading axis of a 2-D ``(ep, tp)`` decode mesh over which an MoE
#: engine's stacked expert weights shard.  Distinct from the training
#: ``expert`` axis for the same trace-attribution reason as :data:`TP`.
EP = "ep"

#: the SERVE-side pipeline-stage axis (singa_tpu/serve/pp.py): a 1-D
#: mesh over which an engine's LAYERS (and the layer axis of its paged
#: KV pool) partition into stages.  Distinct from the training
#: ``pipe`` axis, like :data:`TP`/:data:`EP`.
PP = "pp"

# True while a graph-mode step is being traced under a ShardingPlan;
# constrain() is the identity otherwise (eager compile-time dummy
# forwards run on one device where a mesh constraint is meaningless).
_plan_active = False


def plan_active() -> bool:
    return _plan_active


class _PlanActive:
    """Context manager the graph runner wraps its trace in."""

    def __enter__(self):
        global _plan_active
        self._prev = _plan_active
        _plan_active = True

    def __exit__(self, *exc):
        global _plan_active
        _plan_active = self._prev
        return False


def create_mesh(dp=1, tp=1, sp=1, pp=1, ep=1, devices=None) -> Mesh:
    """Mesh over ``(data, model, seq, pipe, expert)`` axes (size-1 axes are
    kept: sharding over a singleton axis is a no-op, and keeping every
    name means every PartitionSpec in the framework is always valid).

    On a real slice, axis order is layout: the trailing axes vary fastest
    over the device list, so put the heaviest-communication axis (model/
    seq — activation-sized collectives every layer) innermost where
    neighbours share an ICI link, and data (one gradient all-reduce per
    step) outermost, possibly over DCN.
    """
    sizes = dict(dp=int(dp), tp=int(tp), sp=int(sp), pp=int(pp), ep=int(ep))
    n = math.prod(sizes.values())
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"mesh dp*tp*sp*pp*ep={n} needs {n} devices, have "
            f"{len(devices)} — provision a virtual CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"(tests/conftest.py) or shrink the mesh")
    arr = np.asarray(devices[:n]).reshape(
        sizes["dp"], sizes["tp"], sizes["sp"], sizes["pp"], sizes["ep"])
    return Mesh(arr, (DATA, MODEL, SEQ, PIPE, EXPERT))


def create_tp_mesh(tp, devices=None) -> Mesh:
    """1-D serve-side tensor-parallel mesh over the first ``tp``
    devices (axis name :data:`TP`).  The serve TP backend
    (singa_tpu/serve/tp.py) runs every engine executable under a
    ``shard_map`` over this mesh; on a chipless box provision a CPU
    virtual mesh exactly like the training tests do."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if devices is None:
        devices = jax.devices()
    if len(devices) < tp:
        raise ValueError(
            f"tp={tp} needs {tp} devices, have {len(devices)} — "
            f"provision a virtual CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={tp} "
            f"(tests/conftest.py) or lower tp")
    return Mesh(np.asarray(devices[:tp]), (TP,))


def create_ep_mesh(ep, tp=1, devices=None) -> Mesh:
    """2-D serve-side ``(ep, tp)`` mesh over the first ``ep * tp``
    devices: experts shard over :data:`EP` (the outer axis), the dense
    layers' Megatron layout rides :data:`TP` (the inner axis, adjacent
    devices — the heavier per-layer collective).  ``tp=1`` keeps the
    axis (size-1 sharding is a no-op) so one spec set serves every EP
    geometry."""
    if ep < 1 or tp < 1:
        raise ValueError(f"ep and tp must be >= 1, got ep={ep} tp={tp}")
    n = ep * tp
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"ep x tp = {ep} x {tp} = {n} needs {n} devices, have "
            f"{len(devices)} — provision a virtual CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"(tests/conftest.py) or shrink the mesh")
    return Mesh(np.asarray(devices[:n]).reshape(ep, tp), (EP, TP))


def create_pp_mesh(stages, devices=None) -> Mesh:
    """1-D serve-side pipeline mesh over the first ``stages`` devices
    (axis name :data:`PP`): each rank owns one stage's layer slice of
    the decode weights and of the paged KV pool."""
    if stages < 1:
        raise ValueError(f"stages must be >= 1, got {stages}")
    if devices is None:
        devices = jax.devices()
    if len(devices) < stages:
        raise ValueError(
            f"stages={stages} needs {stages} devices, have "
            f"{len(devices)} — provision a virtual CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{stages} (tests/conftest.py) or lower stages")
    return Mesh(np.asarray(devices[:stages]), (PP,))


class ShardingPlan:
    """Maps persistent state + batch inputs to shardings over a mesh.

    Parameter specs come from (highest priority first):
      1. the tensor's own ``partition_spec`` attribute (set by the
         parallel layers in tensor_parallel / moe / pipeline);
      2. ``rules``: ordered ``(regex, PartitionSpec)`` pairs matched
         against the state name;
      3. replicated ``P()``.

    Optimizer slots (``__opt__{param}:{slot}``) inherit their parameter's
    spec — a momentum buffer is laid out exactly like its weight, which
    is what makes the optimizer update fully local (no collective in the
    update, like the reference's per-GPU DistOpt update after allreduce).

    ``shard_inputs``: batch arrays are sharded ``data`` on dim 0 and —
    when the mesh has a real seq axis and the array looks like (B, S,
    ...) tokens — ``seq`` on dim 1.  Override per-model via
    ``input_specs`` (list matched positionally against the step's tensor
    inputs).
    """

    def __init__(self, mesh: Mesh, rules=(), input_specs=None,
                 shard_seq_inputs=True):
        self.mesh = mesh
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.input_specs = input_specs
        self.shard_seq_inputs = bool(shard_seq_inputs)

    # -- mesh facts --------------------------------------------------------
    def axis_size(self, name) -> int:
        return self.mesh.shape.get(name, 1)

    @property
    def world(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def sharding(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    # -- state -------------------------------------------------------------
    def spec_for_state(self, name, t, param_specs=None) -> P:
        spec = getattr(t, "partition_spec", None)
        if spec is not None:
            return spec
        base = name
        if name.startswith("__opt__"):
            base = name[len("__opt__"):].rsplit(":", 1)[0]
            if param_specs and base in param_specs:
                return param_specs[base]
        for pat, s in self.rules:
            if pat.search(base):
                return s
        return P()

    # -- inputs ------------------------------------------------------------
    def spec_for_input(self, arr, index) -> P:
        if self.input_specs is not None:
            return self.input_specs[index]
        if arr.ndim == 0:
            return P()
        dims = [None] * arr.ndim
        if arr.shape[0] % self.axis_size(DATA) == 0:
            dims[0] = DATA
        if (self.shard_seq_inputs and arr.ndim >= 2
                and self.axis_size(SEQ) > 1
                and arr.shape[1] % self.axis_size(SEQ) == 0):
            dims[1] = SEQ
        return P(*dims)

    # -- activation spec helper (used by the parallel layers) --------------
    def act_spec(self, ndim, model_last=False, seq_dim=1) -> P:
        """(B, ..., E)-shaped activation: data on dim 0, seq on ``seq_dim``
        (when the mesh shards sequences), model on the last dim when the
        activation is the output of a column-parallel projection."""
        dims = [None] * ndim
        dims[0] = DATA
        if ndim >= 3 and self.axis_size(SEQ) > 1 and seq_dim < ndim - 1:
            dims[seq_dim] = SEQ
        if model_last:
            dims[-1] = MODEL
        return P(*dims)


def constrain(x, plan: ShardingPlan, spec) -> "autograd.Tensor":
    """Taped sharding-constraint op: identity in eager mode, a GSPMD
    layout pin while a planned graph step is being traced.  The VJP of
    with_sharding_constraint is with_sharding_constraint — gradients
    respect the same layout, so e.g. a column-parallel weight's grad is
    born sharded and never materializes replicated."""
    if not _plan_active:
        return x
    ns = plan.sharding(spec)
    return autograd._op(
        lambda v: jax.lax.with_sharding_constraint(v, ns),
        x, _name="ShardConstraint")
