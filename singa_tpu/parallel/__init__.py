"""Distributed training & model parallelism.

Two complementary paths:

  * reference-parity data parallelism — the rebuild of the NCCL/MPI
    ``Communicator`` (src/io/communicator.cc, unverified) on ICI/DCN
    collectives via mesh + shard_map (communicator.py, dist_opt.py);
  * TPU-native model parallelism the reference never had — a named
    multi-axis mesh with GSPMD sharding plans (sharding.py), Megatron
    tensor parallelism (tensor_parallel.py), and ring-attention
    sequence parallelism (ring_attention.py).
"""

from .sharding import (  # noqa: F401
    AXES, DATA, EXPERT, MODEL, PIPE, SEQ,
    ShardingPlan, constrain, create_mesh,
)
