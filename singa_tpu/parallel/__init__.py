"""Distributed training — the rebuild of the reference's NCCL/MPI
``Communicator`` (src/io/communicator.cc, unverified) on ICI/DCN
collectives via jax mesh + shard_map."""
