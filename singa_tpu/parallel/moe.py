"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh
axis.

The reference has no MoE (SURVEY.md §2.3: expert parallelism "not
required for parity"); this is the TPU-native extension point.  Design
follows the GShard/Switch formulation, which is exactly the shape the
XLA SPMD partitioner was built around:

  * tokens are split into G groups with the group dim sharded over
    ``data`` (GShard's "groups = data shards"): routing and capacity are
    per-group (C = ceil(k·N/G/E·capacity_factor)), so dispatch/expert
    buffers shaped (G,E,C,D) shard ``P(data, expert, …)`` and both the
    buffers and the expert FLOPs SCALE DOWN with the data axis instead
    of being redundantly replicated on every data rank;
  * token-choice top-k gating with a static per-group per-expert
    capacity — static shapes, no dynamic gather/scatter, everything
    tiles onto the MXU;
  * dispatch/combine are one-hot einsums ``(G,n,E,C)×(G,n,D)→(G,E,C,D)``;
    with groups on ``data`` and experts on ``expert``, GSPMD lowers the
    expert-dim resharding to the all-to-all exchange the reference-era
    frameworks hand-code with NCCL;
  * expert FFNs are a single batched einsum over the (G, E, …) leading
    dims — each chip runs only its resident experts on its groups;
  * the standard load-balance auxiliary loss (mean fraction·probability
    product, scaled by E so a uniform router scores 1.0) is exposed as
    ``last_aux_loss`` for the model to add to its objective — it flows
    gradients into the router.

Tokens over capacity are dropped (their combine weight is zero and the
residual path carries them), matching Switch-Transformer semantics.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import amp, autograd
from ..layer import Layer
from ..tensor import Tensor
from . import sharding
from .sharding import EXPERT, P, ShardingPlan

__all__ = ["MoEFFN", "dispatch_load"]


def dispatch_load(dispatch, top_k):
    """Expert-load observability from a dispatch one-hot (the serve
    expert-parallel twins' hook — singa_tpu/serve/ep.py feeds
    ``serve.ep.expert_tokens{engine=,expert=}`` and the dropped-token
    counter from exactly this): ``dispatch`` is the (N, E, C) 0/1
    tensor :func:`_top1_dispatch`/:func:`_top2_dispatch` return.
    Returns ``(tokens_per_expert (E,) int32, dropped int32)`` where
    ``dropped`` counts the top-k assignments capacity bounded away
    (every token makes exactly ``top_k`` assignments; an assignment
    that did not survive is a drop whose output rides the residual
    path).  An imbalanced router shows up here before it shows up as
    latency — the MoE why_slow."""
    kept = jnp.sum(dispatch, axis=(0, 2))                   # (E,)
    n = dispatch.shape[0]
    dropped = top_k * n - jnp.sum(kept)
    return (jnp.round(kept).astype(jnp.int32),
            jnp.round(dropped).astype(jnp.int32))


def _top2_dispatch(probs, capacity):
    """GShard top-2 token-choice routing.

    probs: (N, E) router softmax.  Returns (dispatch, combine, aux):
    dispatch (N, E, C) 0/1, combine (N, E, C) gate-weighted, aux scalar
    load-balance loss.
    """
    n, e = probs.shape
    idx1 = jnp.argmax(probs, axis=-1)                       # (N,)
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)      # (N, E)
    gate1 = jnp.sum(probs * mask1, axis=-1)                 # (N,)

    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    gate2 = jnp.sum(probs2 * mask2, axis=-1)

    # load-balance aux loss (GShard eq. 4 / Switch §2.2): fraction of
    # first-choice tokens per expert × mean router prob, scaled by E so
    # a uniform router gives exactly 1.0 (same convention as top-1)
    frac = jnp.mean(mask1, axis=0)                          # (E,)
    pmean = jnp.mean(probs, axis=0)                         # (E,)
    aux = jnp.sum(frac * pmean) * e

    # positions within each expert: first choices fill first; second
    # choices start after the SURVIVING first choices (min(count1, C)) —
    # offsetting by the raw count would strand free capacity slots
    # behind dropped first-choice overflow
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1        # (N, E)
    count1 = jnp.sum(mask1, axis=0, keepdims=True)          # (1, E)
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2
            + jnp.minimum(count1, capacity)) * mask2

    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    # renormalize the two gates over what survived
    g1 = gate1 * jnp.sum(keep1, axis=-1)
    g2 = gate2 * jnp.sum(keep2, axis=-1)
    denom = g1 + g2
    denom = jnp.where(denom <= 0.0, 1.0, denom)
    g1, g2 = g1 / denom, g2 / denom

    pos1_idx = jnp.sum(pos1, axis=-1).astype(jnp.int32)     # (N,)
    pos2_idx = jnp.sum(pos2, axis=-1).astype(jnp.int32)
    cap1 = jax.nn.one_hot(pos1_idx, capacity, dtype=probs.dtype)
    cap2 = jax.nn.one_hot(pos2_idx, capacity, dtype=probs.dtype)

    d1 = keep1[:, :, None] * cap1[:, None, :]               # (N, E, C)
    d2 = keep2[:, :, None] * cap2[:, None, :]
    dispatch = d1 + d2
    combine = g1[:, None, None] * d1 + g2[:, None, None] * d2
    return dispatch, combine, aux


def _top1_dispatch(probs, capacity):
    """Switch-Transformer top-1 routing."""
    n, e = probs.shape
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)
    gate1 = jnp.sum(probs * mask1, axis=-1)

    frac = jnp.mean(mask1, axis=0)
    pmean = jnp.mean(probs, axis=0)
    aux = jnp.sum(frac * pmean) * e

    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1
    keep1 = mask1 * (pos1 < capacity)
    pos1_idx = jnp.sum(pos1, axis=-1).astype(jnp.int32)
    cap1 = jax.nn.one_hot(pos1_idx, capacity, dtype=probs.dtype)
    dispatch = keep1[:, :, None] * cap1[:, None, :]
    combine = gate1[:, None, None] * dispatch
    return dispatch, combine, aux


class MoEFFN(Layer):
    """Drop-in replacement for a transformer FFN: E expert MLPs with
    top-k routing; experts sharded over the ``expert`` mesh axis.

    After ``forward``, ``last_aux_loss`` holds the taped load-balance
    loss — add ``aux_weight * moe.last_aux_loss`` to the training
    objective (see tests/test_moe.py::MoEModel for the wiring)."""

    def __init__(self, num_experts, intermediate,
                 plan: ShardingPlan | None = None, top_k=2,
                 capacity_factor=1.25, activation="gelu", remat=False,
                 groups=None):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError("top_k must be 1 (Switch) or 2 (GShard)")
        self.num_experts = int(num_experts)
        self.intermediate = int(intermediate)
        self.plan = plan
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        self.remat = bool(remat)  # recompute dispatch/experts in bwd
        # routing-group count: default = plan's data-axis size (1 without
        # a plan); explicit override lets a serial oracle reproduce a
        # sharded run's grouped-routing math exactly
        self.groups = None if groups is None else int(groups)
        self.last_aux_loss = None

    def initialize(self, x):
        d = x.shape[-1]
        e, f = self.num_experts, self.intermediate
        dt = amp.param_dtype(x.data.dtype)
        dev = x.device

        def param(shape, std, spec):
            t = Tensor(shape, device=dev, dtype=dt, requires_grad=True,
                       stores_grad=True)
            t.gaussian(0.0, std)
            t.partition_spec = spec
            return t

        # router stays replicated (tiny); experts shard over `expert`
        self.Wg = param((d, e), 1.0 / math.sqrt(d), P())
        self.W1 = param((e, d, f), math.sqrt(2.0 / d), P(EXPERT, None, None))
        self.b1 = param((e, f), 0.0, P(EXPERT, None))
        self.W2 = param((e, f, d), math.sqrt(2.0 / f), P(EXPERT, None, None))
        self.b2 = param((e, d), 0.0, P(EXPERT, None))

    def _num_groups(self, n):
        """Groups = data-axis size (GShard): routing is per-group and the
        group dim shards over ``data``, so expert buffers/FLOPs scale
        with dp.  Plan-less (single-chip) use runs one global group."""
        if self.groups is not None:
            g = self.groups
        elif self.plan is None:
            return 1
        else:
            g = self.plan.axis_size(sharding.DATA)
        if n % g != 0:
            raise ValueError(
                f"MoE token count {n} not divisible by data-axis size {g}")
        return g

    def _capacity(self, n_per_group):
        return max(1, int(math.ceil(
            self.top_k * n_per_group / self.num_experts
            * self.capacity_factor)))

    def forward(self, x):
        b, s, d = x.shape
        n = b * s
        g = self._num_groups(n)
        nl = n // g  # tokens per group
        cap = self._capacity(nl)
        plan = self.plan
        act = getattr(jax.nn, self.activation)
        route = jax.vmap(_top2_dispatch if self.top_k == 2
                         else _top1_dispatch, in_axes=(0, None))

        def constrain(a, spec):
            if plan is not None and sharding.plan_active():
                return jax.lax.with_sharding_constraint(
                    a, plan.sharding(spec))
            return a

        def f(xv, wg, w1, b1, w2, b2):
            xt = xv.reshape(g, nl, d)
            xt = constrain(xt, P(sharding.DATA, None, None))
            # route in fp32 — bf16 cumsum positions go wrong past 256
            probs = jax.nn.softmax(
                (xt @ wg.astype(xt.dtype)).astype(jnp.float32), axis=-1)
            dispatch, combine, aux = route(probs, cap)   # (G,n,E,C) ×2, (G,)
            dispatch = dispatch.astype(xt.dtype)
            combine = combine.astype(xt.dtype)
            # dispatch: tokens -> (G, E, C, D); resharding E onto the
            # expert axis is the data->expert all-to-all under GSPMD
            ein = jnp.einsum("gnec,gnd->gecd", dispatch, xt)
            ein = constrain(ein, P(sharding.DATA, EXPERT, None, None))
            h = act(jnp.einsum("gecd,edf->gecf", ein, w1)
                    + b1[None, :, None, :])
            out = jnp.einsum("gecf,efd->gecd", h, w2) + b2[None, :, None, :]
            out = constrain(out, P(sharding.DATA, EXPERT, None, None))
            # combine: (G, E, C, D) -> tokens (the reverse all-to-all)
            y = jnp.einsum("gnec,gecd->gnd", combine, out)
            return y.reshape(b, s, d), jnp.mean(aux).astype(jnp.float32)

        apply = autograd.checkpoint_op if self.remat else autograd._op
        y, aux = apply(
            f, x, self.Wg, self.W1, self.b1, self.W2, self.b2,
            _name="MoEFFN")
        self.last_aux_loss = aux
        return y
