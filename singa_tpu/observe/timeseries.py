"""Windowed telemetry: bounded (timestamp, value) rings over registry
metrics — the "last 60 seconds" truth next to the all-time truth.

Everything the registry exports is ALL-TIME (monotone counters,
lifetime histograms): perfect for audits, useless for control loops —
"the error budget is burning 14x too fast over the last minute" needs
a rate over a window, and "TTFT p99 over the last 10 minutes" needs
quantiles over recent samples only.  This module adds that layer
without touching the existing schema:

* :class:`WindowRing` — a bounded ring of ``(t, value)`` samples with
  an injectable clock.  O(capacity) memory forever; reads scan only
  the in-window tail.
* :class:`WindowedFamily` — windowed views over every metric of one
  NAME (all label sets), created by
  ``registry.windowed(name, windows=(60, 600, 3600))``.  Counters
  record their cumulative value on every ``inc`` (``rate(window)`` =
  growth over the window / window); histograms record each observed
  value (``quantile``/``mean`` over the in-window samples, ``rate`` =
  events/s); gauges record each written level (``mean``/``quantile``).
  Metrics registered LATER under the same name (a new engine label
  from a fleet scale-up) attach automatically, and
  ``MetricsRegistry.remove`` detaches their rings — a retired
  replica's windowed series disappears with its all-time series
  instead of freezing at its last value.

The windowed values ride the existing exporters as SIBLING gauges
(``<name>_rate_60s{...}``-style — see ``export.prometheus_text``) and
``health_report()["windowed"]``; the all-time families are unchanged
(add-only).  ``observe/slo.py`` builds multi-window burn-rate alerts
on exactly this surface.

Clock discipline: every read method takes ``now=None`` (defaults to
the ring's clock) so tests and pollers are deterministic under a fake
clock.  A clock that goes BACKWARDS never corrupts a ring: samples
are kept in append order, the in-window scan walks from the newest
sample toward the oldest and stops at the first one older than
``now - window`` — a sample stamped "in the future" (recorded before
the clock stepped back) simply counts as in-window.
"""

from __future__ import annotations

import collections
import time

from ..utils.metrics import percentile as _percentile

__all__ = ["WindowRing", "WindowedFamily", "DEFAULT_WINDOWS",
           "DEFAULT_RING_CAPACITY"]

#: default window ladder (seconds): 1m / 10m / 1h — the Google-SRE
#: alerting windows' order of magnitude, overridable per family.
DEFAULT_WINDOWS = (60.0, 600.0, 3600.0)

#: default per-ring sample bound.  4096 samples cover an hour at >1
#: event/s; beyond that the oldest samples age out and the longest
#: windows degrade toward "since the oldest retained sample" — O(ring)
#: memory forever is the contract, not unbounded fidelity.
DEFAULT_RING_CAPACITY = 4096


class WindowRing:
    """Bounded ring of ``(t, value)`` samples.

    ``kind`` decides the arithmetic:

    * ``"counter"`` — samples are CUMULATIVE values (appended on every
      ``inc``); :meth:`rate` is the value growth across the window
      divided by the window.  The ring tracks the last value EVICTED
      (``_floor``) so a wrapped ring still has a baseline, and the
      value at attach time so a counter adopted mid-life doesn't
      credit its history to the first window.
    * ``"event"`` — samples are per-event values (histogram
      observations); :meth:`rate` is events/s in the window and
      :meth:`quantile`/:meth:`mean` summarize the in-window values.
    * ``"level"`` — samples are written levels (gauge sets);
      :meth:`mean`/:meth:`quantile` summarize, :meth:`rate` is the
      write rate (rarely interesting, but defined).
    """

    __slots__ = ("kind", "capacity", "_clock", "_buf", "_floor_t",
                 "_floor_v")

    def __init__(self, kind="event", capacity=DEFAULT_RING_CAPACITY,
                 clock=time.monotonic, baseline=0.0):
        if kind not in ("counter", "event", "level"):
            raise ValueError(f"unknown ring kind {kind!r}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.kind = kind
        self.capacity = int(capacity)
        self._clock = clock
        self._buf = collections.deque()
        # baseline: the cumulative value "before the oldest retained
        # sample" — starts at the metric's value when the ring
        # attached, advances as samples age out of the ring
        self._floor_t = clock()
        self._floor_v = float(baseline)

    def __len__(self):
        return len(self._buf)

    def append(self, value, t=None):
        if t is None:
            t = self._clock()
        if len(self._buf) >= self.capacity:
            ft, fv = self._buf.popleft()
            self._floor_t, self._floor_v = ft, fv
        self._buf.append((t, float(value)))

    def _tail(self, window, now):
        """In-window ``(t, v)`` pairs, oldest-first.  Scans newest ->
        oldest and stops at the first sample older than the cutoff;
        with a monotone clock this is exact, and a backwards clock can
        only hide samples OLDER than the break point (never corrupt
        the ring) — a sample stamped after ``now`` counts in-window.
        Reads snapshot the buffer first: the registry promises
        cross-thread use (writer threads append while a scrape or
        poll reads), and iterating a live deque under mutation
        raises."""
        cutoff = now - window
        buf = tuple(self._buf)
        out = []
        for t, v in reversed(buf):
            if t < cutoff:
                break
            out.append((t, v))
        out.reverse()
        return out

    def values(self, window, now=None) -> list:
        """In-window sample values, oldest-first."""
        if now is None:
            now = self._clock()
        return [v for _, v in self._tail(window, now)]

    def rate(self, window, now=None) -> float:
        """Per-second rate over the window.  Counter rings: value
        growth / window (0.0 when nothing changed — an idle counter
        has rate 0, not nan).  Event/level rings: samples / window."""
        if now is None:
            now = self._clock()
        window = float(window)
        if window <= 0:
            raise ValueError(f"window must be > 0, got {window}")
        if self.kind != "counter":
            return len(self._tail(window, now)) / window
        # one snapshot serves the whole computation (see _tail)
        buf = tuple(self._buf)
        if not buf:
            return 0.0
        latest = buf[-1][1]
        cutoff = now - window
        if buf[-1][0] >= cutoff:
            # baseline = cumulative value AT the window's start: the
            # last retained sample at/before the cutoff, else the
            # eviction/attach floor.  (A sample exactly ON the cutoff
            # is the baseline, so only growth strictly inside the
            # window counts — matching the in-window scan, which also
            # keeps the boundary sample as the reference point.)
            baseline = self._floor_v
            for t, v in buf:
                if t <= cutoff:
                    baseline = v
                else:
                    break
        else:
            baseline = latest  # no in-window growth
        # clamp: a counter reset (or a backwards clock interleaving
        # samples) must never export a negative rate
        return max(latest - baseline, 0.0) / window

    def mean(self, window, now=None) -> float:
        vals = self.values(window, now)
        return sum(vals) / len(vals) if vals else float("nan")

    def quantile(self, q, window, now=None) -> float:
        """Nearest-rank quantile (``q`` in [0, 1]) over the in-window
        samples; nan when the window is empty (same contract as
        ``LatencySeries``)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        return _percentile(self.values(window, now), q * 100.0)


class WindowedFamily:
    """Windowed views over every metric of one registry NAME.

    Built by ``MetricsRegistry.windowed(name, ...)`` — do not
    construct directly.  Holds one :class:`WindowRing` per label set
    (attached when the metric is, detached when the metric is removed)
    and aggregates reads across them: counter rates SUM (the fleet
    view), event samples MERGE before the quantile.  ``match``
    filters by a label subset (``match={"kind": "ttft"}``)."""

    def __init__(self, name, kind, windows=DEFAULT_WINDOWS,
                 capacity=DEFAULT_RING_CAPACITY, clock=time.monotonic):
        ws = tuple(float(w) for w in windows)
        if not ws or any(w <= 0 for w in ws):
            raise ValueError(
                f"windows must be non-empty positive seconds, got "
                f"{windows}")
        self.name = name
        # "counter" | "gauge" | "histogram" — None until the first
        # metric attaches (a family can be registered BEFORE its name
        # exists; the first attach resolves the arithmetic)
        self.kind = kind
        self.windows = ws
        self.capacity = int(capacity)
        self.clock = clock
        self.rings = {}  # label tuple (sorted (k, v) pairs) -> ring
        # label tuple -> the EXACT hook object registered on a
        # histogram's series: ``ring.append`` is a fresh bound-method
        # object on every attribute access, and remove_hook filters
        # by identity, so detach must present the same object
        self._series_hooks = {}

    # -- attachment (registry-driven) -----------------------------------
    def _attach(self, metric):
        """Create and wire a ring for ``metric`` (idempotent)."""
        if self.kind is None:
            self.kind = metric.KIND
        if metric.labels in self.rings:
            return self.rings[metric.labels]
        baseline = metric.value if self.kind == "counter" else 0.0
        ring = WindowRing(
            "event" if self.kind == "histogram" else
            ("counter" if self.kind == "counter" else "level"),
            capacity=self.capacity, clock=self.clock,
            baseline=baseline)
        self.rings[metric.labels] = ring
        if self.kind == "histogram":
            # adopters record into the series directly, so the series'
            # record hook is the one point that sees every value
            hook = ring.append
            self._series_hooks[metric.labels] = hook
            metric.series.add_hook(hook)
        else:
            # counters/gauges: every write appends the NEW value
            metric._rings = metric._rings + (ring,)
        return ring

    def _detach_metric(self, metric):
        """Unwire ``metric``'s ring (registry.remove / unwindow): the
        series hook or the metric's ring tuple, then the ring itself —
        a retired metric's windowed series must disappear, not freeze
        or keep consuming records."""
        ring = self.rings.pop(metric.labels, None)
        if ring is None:
            return
        hook = self._series_hooks.pop(metric.labels, None)
        if hook is not None:
            metric.series.remove_hook(hook)
        else:
            metric._rings = tuple(r for r in metric._rings
                                  if r is not ring)

    # -- reads ----------------------------------------------------------
    def _selected(self, match):
        # snapshot first: a concurrent scale-up attaches rings while
        # a scrape/poll reads (same discipline as export's copy)
        rings = dict(self.rings)
        if match is None:
            return list(rings.values())
        want = {(str(k), str(v)) for k, v in match.items()}
        return [r for labels, r in rings.items()
                if want <= set(labels)]

    def rate(self, window, now=None, match=None) -> float:
        """Summed per-second rate across the (matching) label sets."""
        if now is None:
            now = self.clock()
        return sum(r.rate(window, now) for r in self._selected(match))

    def values(self, window, now=None, match=None) -> list:
        if now is None:
            now = self.clock()
        out = []
        for r in self._selected(match):
            out.extend(r.values(window, now))
        return out

    def mean(self, window, now=None, match=None) -> float:
        vals = self.values(window, now, match)
        return sum(vals) / len(vals) if vals else float("nan")

    def quantile(self, q, window, now=None, match=None) -> float:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        return _percentile(self.values(window, now, match), q * 100.0)

    def section(self, now=None) -> dict:
        """JSON-able health view: per-window aggregates in the shape
        the window's arithmetic supports (counter: rate; histogram:
        rate + p50/p99/mean; gauge: mean)."""
        if now is None:
            now = self.clock()
        out = {"kind": self.kind, "series": len(self.rings),
               "windows": {}}
        for w in self.windows:
            key = _wlabel(w)
            if self.kind in ("counter", None):
                out["windows"][key] = {"rate": self.rate(w, now)}
            elif self.kind == "histogram":
                out["windows"][key] = {
                    "rate": self.rate(w, now),
                    "mean": self.mean(w, now),
                    "p50": self.quantile(0.5, w, now),
                    "p99": self.quantile(0.99, w, now),
                }
            else:
                out["windows"][key] = {"mean": self.mean(w, now)}
        return out


def _wlabel(window) -> str:
    """``60`` -> ``"60"``, ``2.5`` -> ``"2.5"`` — the window-second
    key used in sibling-gauge names and section dicts."""
    w = float(window)
    return str(int(w)) if w == int(w) else str(w)
