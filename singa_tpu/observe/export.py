"""Exporters for the ``observe`` buffer and registry.

Three formats, three audiences:

* :func:`write_jsonl` / :func:`jsonl_lines` — the raw span/event
  records, one JSON object per line; greppable, streamable, the
  machine-diffable archive format.
* :func:`chrome_trace` / :func:`write_chrome_trace` — Chrome
  trace-event JSON (the ``{"traceEvents": [...]}`` object form),
  loadable directly in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``.  Tracks: one row per SUBSYSTEM (the span
  ``cat`` — train/serve/comms/snapshot/...), named via ``thread_name``
  metadata events, with the originating Python thread preserved in
  each span's args.  Timestamps are microseconds per the spec.
* :func:`prometheus_text` — text exposition of a
  :class:`~singa_tpu.observe.registry.MetricsRegistry` (counters,
  gauges, and histograms with cumulative ``_bucket{le=...}`` series),
  scrapeable by any Prometheus agent.  Metric names are sanitized to
  the exposition charset and prefixed ``singa_tpu_``.  Histograms
  export the full bucket ladder (``registry.DEFAULT_BUCKETS`` or the
  per-metric override) precisely so that cross-process
  ``histogram_quantile(0.99, sum(rate(x_bucket[5m])) by (le))`` works
  over a fleet of replicas — the precomputed nearest-rank quantiles
  (kept as a sibling ``<name>_quantile`` gauge family, the
  single-process view) cannot be aggregated.

The request-tracing round adds :func:`request_trace_events`: the
:class:`~singa_tpu.observe.requests.RequestLedger`'s per-request
timelines as Chrome-trace tracks (one row per request: queue /
prefill / decode phase spans per hop, flow arrows linking
cross-replica hops).  ``chrome_trace(requests=...)`` merges them into
the span trace under their own ``requests`` process group.

All exporters take explicit ``events``/``reg`` arguments and default
to the live trace buffer / default registry, so tests can run them on
synthetic data.
"""

from __future__ import annotations

import json
import math
import os
import re
import time as _time

from . import trace as _trace
from .registry import Counter, Histogram, registry as _registry

__all__ = ["jsonl_lines", "write_jsonl", "chrome_trace",
           "write_chrome_trace", "request_trace_events",
           "step_trace_events", "prometheus_text", "write_prometheus",
           "json_sanitize"]


def json_sanitize(obj):
    """Deep copy with non-finite floats (nan/inf) replaced by None, so
    the result serializes as STRICT JSON (``json.dumps(...,
    allow_nan=False)`` passes).  Python's encoder would emit the
    non-standard ``NaN`` token, which jq / JSON.parse / serde all
    reject — an honest in-memory ``mfu=nan`` must become ``null`` on
    the wire, not a file only Python can read.  Used by the benches'
    report/health writers and the monitor's crash bundles."""
    if isinstance(obj, dict):
        return {k: json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_sanitize(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    return obj


# ---------------------------------------------------------------------------
# JSONL
# ---------------------------------------------------------------------------

def jsonl_lines(events=None):
    """Yield one JSON line per buffered event (record schema as
    documented in ``trace.py``)."""
    if events is None:
        events = _trace.events()
    for rec in events:
        yield json.dumps(rec, default=str)


def write_jsonl(path, events=None):
    """Write the event log as JSONL; returns the event count."""
    n = 0
    with open(path, "w") as f:
        for line in jsonl_lines(events):
            f.write(line + "\n")
            n += 1
    return n


# ---------------------------------------------------------------------------
# Chrome trace-event JSON (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

def request_trace_events(entries, pid=1) -> list:
    """Per-request Chrome-trace tracks from sealed
    :class:`~singa_tpu.observe.requests.RequestLedger` entries: one
    tid per request, phase spans per hop (``queue`` submit→admission,
    ``prefill`` admission→first token, ``decode`` first token→hop
    end), rejection instants, and FLOW events (``ph: s``/``f``)
    drawing an arrow across each requeue/failover/hedge hop boundary —
    in Perfetto a failover-requeued request reads as one line with a
    visible jump between replicas.  Rides its own ``requests``
    process (``pid``) so the per-subsystem span tracks (pid 0) stay
    untouched."""
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "requests"}}]
    flow_id = 0
    for tid, e in enumerate(entries):
        rid = e["request_id"]
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"req {rid}"}})
        hops = e.get("hops") or []
        for j, h in enumerate(hops):
            if j + 1 < len(hops):
                hop_end = hops[j + 1]["t_submit"]
            elif e.get("t_retire") is not None:
                hop_end = e["t_retire"]
            else:
                hop_end = h["t_submit"]
            base = {"request": rid, "hop": j,
                    "engine": h.get("engine"),
                    "replica": h.get("replica"),
                    "host": h.get("host"), "via": h.get("via")}

            def span(name, t0, t1, **extra):
                if t0 is None or t1 is None or t1 < t0:
                    return
                out.append({"name": name, "cat": "request", "ph": "X",
                            "pid": pid, "tid": tid, "ts": t0 * 1e6,
                            "dur": (t1 - t0) * 1e6,
                            "args": dict(base, **extra)})

            t_admit, t_first = h.get("t_admit"), h.get("t_first_token")
            span("queue", h["t_submit"],
                 t_admit if t_admit is not None else hop_end,
                 depth=h.get("queue_depth_at_enqueue"))
            span("prefill", t_admit, t_first,
                 kind=h.get("admit_kind"),
                 hit_tokens=h.get("hit_tokens"),
                 chunks=len(h.get("chunks") or ()))
            span("decode", t_first, hop_end, tokens=h.get("tokens"))
            rej = h.get("reject")
            if rej is not None:
                out.append({"name": "rejected", "cat": "request",
                            "ph": "i", "s": "t", "pid": pid,
                            "tid": tid, "ts": rej["t"] * 1e6,
                            "args": dict(base,
                                         reason=rej.get("reason"),
                                         started=rej.get("started"))})
            if j > 0:
                # flow arrow: previous hop's end -> this hop's submit
                flow_id += 1
                out.append({"name": "hop", "cat": "request", "ph": "s",
                            "pid": pid, "tid": tid, "id": flow_id,
                            "ts": h["t_submit"] * 1e6 - 1,
                            "args": base})
                out.append({"name": "hop", "cat": "request", "ph": "f",
                            "bp": "e", "pid": pid, "tid": tid,
                            "id": flow_id, "ts": h["t_submit"] * 1e6,
                            "args": base})
    return out


def step_trace_events(records, pid=2) -> list:
    """Dual-lane step-anatomy tracks from
    :func:`~singa_tpu.observe.stepprof.records` entries: per engine a
    HOST lane (one ``X`` slice per host segment piece, named by
    segment, the step's wall as a ``step N`` parent slice) stacked
    directly above a DEVICE lane (one slice per dispatch→ready
    window).  The bubble is what you SEE: every gap in the device lane
    under host activity is device idle time — ROADMAP item 5's target
    rendered as empty pixels.  Rides its own ``step anatomy`` process
    (``pid``) next to the subsystem (pid 0) and request (pid 1)
    tracks."""
    out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "step anatomy"}}]
    labels = []
    for rec in records:
        if rec["engine"] not in labels:
            labels.append(rec["engine"])
    lanes = {}
    for i, lbl in enumerate(labels):
        host_tid, dev_tid = 2 * i, 2 * i + 1
        lanes[lbl] = (host_tid, dev_tid)
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": host_tid,
                    "args": {"name": f"e{lbl} host"}})
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": dev_tid,
                    "args": {"name": f"e{lbl} device"}})
    for rec in records:
        host_tid, dev_tid = lanes[rec["engine"]]
        base = {"engine": rec["engine"], "step": rec["step"]}
        # the step's wall as the host lane's top-level slice: nested
        # segment pieces render inside it, and its args carry the
        # sealed totals (the hover-card summary)
        out.append({"name": f"step {rec['step']}", "cat": "step.host",
                    "ph": "X", "pid": pid, "tid": host_tid,
                    "ts": rec["t0"] * 1e6, "dur": rec["wall_s"] * 1e6,
                    "args": dict(base,
                                 bubble_frac=round(
                                     rec["bubble_frac"], 4),
                                 host_s=rec["host_s"],
                                 device_s=rec["device_s"])})
        for name, t0, dur in rec["pieces"]:
            if name == "device" or dur <= 0.0:
                continue  # device windows render on their own lane
            out.append({"name": name, "cat": "step.host", "ph": "X",
                        "pid": pid, "tid": host_tid, "ts": t0 * 1e6,
                        "dur": dur * 1e6, "args": base})
        for t0, dur in rec["device_windows"]:
            out.append({"name": "device", "cat": "step.device",
                        "ph": "X", "pid": pid, "tid": dev_tid,
                        "ts": t0 * 1e6, "dur": dur * 1e6,
                        "args": base})
    return out


def chrome_trace(events=None, metadata=None, requests=None,
                 steps=None) -> dict:
    """Build the trace-event object: spans as complete ("X") events,
    instants as "i", one tid per subsystem category with a
    ``thread_name`` row label.  ``metadata`` is merged into the
    top-level ``otherData``.  ``requests``: optional sealed
    request-ledger entries rendered as per-request tracks
    (:func:`request_trace_events`) in the same document.  ``steps``:
    optional step-anatomy ring records
    (``stepprof.records()``) rendered as dual host/device lanes per
    engine (:func:`step_trace_events`)."""
    if events is None:
        events = _trace.events()
    cats = []
    for rec in events:
        if rec["cat"] not in cats:
            cats.append(rec["cat"])
    tid_of = {c: i for i, c in enumerate(cats)}
    out = []
    for c, tid in tid_of.items():
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": tid, "args": {"name": c}})
    for rec in events:
        args = dict(rec["args"] or {})
        args["thread"] = rec["tid"]
        if rec.get("parent"):
            args["parent"] = rec["parent"]
        ev = {"name": rec["name"], "cat": rec["cat"], "ph": rec["ph"],
              "pid": 0, "tid": tid_of[rec["cat"]],
              "ts": rec["ts"] * 1e6, "args": args}
        if rec["ph"] == "X":
            ev["dur"] = rec["dur"] * 1e6
        else:
            ev["s"] = "t"  # instant scoped to its track
        out.append(ev)
    if requests:
        out.extend(request_trace_events(requests, pid=1))
    if steps:
        out.extend(step_trace_events(steps, pid=2))
    doc = {"traceEvents": out, "displayTimeUnit": "ms",
           "otherData": {"source": "singa_tpu.observe",
                         "dropped_events": _trace.dropped()}}
    if requests:
        doc["otherData"]["request_tracks"] = len(requests)
    if steps:
        doc["otherData"]["step_records"] = len(steps)
    if metadata:
        doc["otherData"].update(metadata)
    return doc


def write_chrome_trace(path, events=None, metadata=None,
                       requests=None, steps=None) -> int:
    """Write the Chrome trace JSON; returns the trace-event count
    (metadata rows included)."""
    doc = chrome_trace(events, metadata, requests=requests,
                       steps=steps)
    with open(path, "w") as f:
        # default=str: span args routinely carry numpy/jax scalars; a
        # trace must never be lost at export time over a dtype
        json.dump(doc, f, default=str)
    return len(doc["traceEvents"])


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "singa_tpu_"

# process start (module import — the observe layer loads with the
# package), the singa_tpu_process_uptime_seconds zero point
_T0 = _time.monotonic()


def _build_info_labels():
    """(key, value) pairs for the ``singa_tpu_build_info`` gauge:
    package version, jax version, and the active backend.  Standard
    scrape-target hygiene — a dashboard joining on build_info can
    split any regression by deploy.  Backend resolution never
    INITIALIZES a backend (reads the platform env/config only), so
    scraping cannot allocate a TPU."""
    try:
        from .. import __version__ as ver
    except Exception:
        ver = "unknown"
    try:
        import jax
        jver = jax.__version__
        backend = (os.environ.get("JAX_PLATFORMS")
                   or os.environ.get("JAX_PLATFORM_NAME") or "auto")
    except Exception:
        jver, backend = "absent", "none"
    return [("version", str(ver)), ("jax", jver),
            ("backend", backend)]


def _prom_name(name: str) -> str:
    n = _NAME_OK.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return _PREFIX + n


def _prom_labels(labels, extra=()):
    items = list(labels) + list(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (_NAME_OK.sub("_", k),
                     str(v).replace("\\", r"\\").replace('"', r'\"'))
        for k, v in items)
    return "{" + body + "}"


def _prom_num(v) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v) if v != int(v) else str(int(v))


def prometheus_text(reg=None) -> str:
    """Render a registry in the Prometheus text exposition format.
    Histograms are exposed as real TYPE-histogram families: cumulative
    ``_bucket{le=...}`` series over the metric's bucket ladder
    (``registry.DEFAULT_BUCKETS`` or the per-metric override) ending
    in ``le="+Inf"`` == ``_count``, plus ``_sum``/``_count`` — the
    form ``histogram_quantile()`` can aggregate ACROSS a fleet of
    scraped replicas, which the previous summary-only exposition could
    not.  The nearest-rank p50/p99 each process already computes ride
    along as a sibling ``<name>_quantile`` gauge family (the exact
    single-process view; conformant scrapers reject quantile samples
    inside a histogram family, hence the separate name)."""
    if reg is None:
        reg = _registry()
    by_name = {}
    for m in reg.metrics():
        by_name.setdefault(m.name, []).append(m)
    lines = []
    for name in sorted(by_name):
        group = by_name[name]
        pname = _prom_name(name)
        kind = group[0].KIND
        # counter samples carry the _total suffix, and the classic
        # text format (prometheus_client convention) declares TYPE/
        # HELP under the SAMPLE name — a TYPE under the bare name
        # would describe a family with zero samples
        decl = pname + "_total" if kind == "counter" else pname
        help_ = next((m.help for m in group if m.help), "")
        if help_:
            lines.append(f"# HELP {decl} {help_}")
        lines.append(f"# TYPE {decl} {kind}")
        for m in group:
            if isinstance(m, Histogram):
                s = m.series
                for le, c in m.bucket_counts():
                    lines.append(
                        pname + "_bucket"
                        + _prom_labels(m.labels, [("le", _prom_num(le))])
                        + " " + _prom_num(c))
                # running total, NOT sum(s.values): once the retained
                # window is bounded, a windowed sum next to the
                # all-time _count would make rate(_sum)/rate(_count)
                # lie about the mean
                lines.append(pname + "_sum" + _prom_labels(m.labels)
                             + " " + _prom_num(s.total_sum))
                lines.append(pname + "_count" + _prom_labels(m.labels)
                             + " " + _prom_num(s.count))
            else:
                suffix = "_total" if isinstance(m, Counter) else ""
                lines.append(pname + suffix + _prom_labels(m.labels)
                             + " " + _prom_num(m.value))
        if kind == "histogram":
            # sibling family for the exact in-process quantiles
            lines.append(f"# TYPE {pname}_quantile gauge")
            for m in group:
                for q in (0.5, 0.99):
                    lines.append(
                        pname + "_quantile"
                        + _prom_labels(m.labels, [("quantile", q)])
                        + " " + _prom_num(m.series.percentile(q * 100)))
    lines.extend(_windowed_lines(reg))
    # scrape-target hygiene: build identity + process uptime, so any
    # dashboard can join a regression onto a deploy and rate() the
    # target's restarts
    lines.append("# HELP singa_tpu_build_info build identity "
                 "(version/jax/backend); always 1")
    lines.append("# TYPE singa_tpu_build_info gauge")
    lines.append("singa_tpu_build_info"
                 + _prom_labels(_build_info_labels()) + " 1")
    lines.append("# HELP singa_tpu_process_uptime_seconds seconds "
                 "since the observe layer loaded in this process")
    lines.append("# TYPE singa_tpu_process_uptime_seconds gauge")
    lines.append("singa_tpu_process_uptime_seconds "
                 + _prom_num(_time.monotonic() - _T0))
    return "\n".join(lines) + "\n"


def _windowed_lines(reg) -> list:
    """Sibling-gauge exposition for every windowed family
    (observe.timeseries): ``<name>_rate_60s``-style names, one sample
    per label set per window, each family with its own HELP/TYPE
    block.  The all-time families above are untouched — windowed
    truth rides NEXT TO them, never instead of them."""
    from .timeseries import _wlabel

    lines = []
    fams = reg.windowed_families()
    for name in sorted(fams):
        wf = fams[name]
        pname = _prom_name(name)
        if wf.kind == "histogram":
            cols = (("rate", "rate", "in-window events per second"),
                    ("p50", "q50", "nearest-rank p50 over the window"),
                    ("p99", "q99", "nearest-rank p99 over the window"))
        elif wf.kind == "gauge":
            cols = (("mean", "mean", "mean written level over the "
                                     "window"),)
        else:
            cols = (("rate", "rate", "counter growth per second over "
                                     "the window"),)
        now = wf.clock()
        rings = dict(wf.rings)  # scale-ups attach concurrently
        for col, _, help_ in cols:
            for w in wf.windows:
                # _wlabel of a fractional window carries a dot, which
                # is illegal in a metric NAME (fine in label values) —
                # sanitize or one bad window poisons the whole scrape
                fam = _NAME_OK.sub("_",
                                   f"{pname}_{col}_{_wlabel(w)}s")
                lines.append(
                    f"# HELP {fam} windowed sibling of {pname}: "
                    f"{help_} ({_wlabel(w)}s window)")
                lines.append(f"# TYPE {fam} gauge")
                for labels in sorted(rings):
                    ring = rings[labels]
                    if col == "rate":
                        v = ring.rate(w, now)
                    elif col == "mean":
                        v = ring.mean(w, now)
                    else:
                        v = ring.quantile(
                            0.5 if col == "p50" else 0.99, w, now)
                    lines.append(fam + _prom_labels(labels) + " "
                                 + _prom_num(v))
    return lines


def write_prometheus(path, reg=None) -> str:
    text = prometheus_text(reg)
    with open(path, "w") as f:
        f.write(text)
    return text
