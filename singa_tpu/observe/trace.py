"""Span tracing — the event half of ``singa_tpu.observe``.

Dapper-style host-side spans over the stack's hot paths (graph-mode
compile/replay, optimizer update, collectives at trace time, async
checkpoint, serve prefill/decode/retire), answering "where did this
step's time go?" with one buffer that every exporter in ``export.py``
reads (JSONL, Chrome trace-event JSON for Perfetto, and — for the
registry — Prometheus text).

Design constraints, in priority order:

1. **near-zero cost when disabled** — ``span()``/``event()`` are one
   module-global flag check; ``span()`` returns a shared singleton
   no-op context manager, so the disabled fast path allocates nothing
   (tests assert identity).  Instrumentation can therefore live
   permanently in hot loops (the serve engine's per-step path,
   ``_GraphRunner.run``).
2. **injectable clock** — ``enable(clock=...)`` takes any ``()->
   float`` seconds callable, making span timestamps/durations exactly
   deterministic in tests (the same pattern the serve engine uses for
   its scheduling clock).
3. **thread-aware** — spans nest per thread (a thread-local stack
   tracks depth and parent), and the buffer append is a single CPython
   list.append (atomic under the GIL), so the async-checkpoint writer
   thread and the main loop can trace concurrently without locks.

Spans are recorded as COMPLETE events at exit (Chrome "X" phase: one
record with ``ts`` + ``dur``) rather than begin/end pairs — half the
buffer traffic, and exporters never see an unmatched begin.  A span
that is still open when the buffer is drained is simply absent; wrap
the drain in the outermost scope you care about.

Event record schema (plain dicts, stable keys)::

    {"name": str, "cat": str, "ph": "X" | "i",
     "ts": float seconds, "dur": float seconds ("X" only),
     "tid": str thread name, "depth": int, "parent": str | None,
     "args": dict | None}

Usage::

    from singa_tpu import observe
    observe.enable()
    with observe.span("train/step", cat="train", step=i) as sp:
        ...
        sp.set(loss=float(loss))           # attach args mid-span
    observe.event("cache/miss", cat="train", key=k)

    @observe.traced                        # or @observe.traced("name")
    def prefill(...): ...

    observe.export.write_chrome_trace("/tmp/trace.json")
"""

from __future__ import annotations

import functools
import threading
import time

__all__ = ["enable", "disable", "is_enabled", "clear", "drain",
           "events", "span", "event", "traced", "set_max_events",
           "dropped"]

# Module-global fast path: `if not _active: return _NULL_SPAN` is the
# ENTIRE disabled cost of a span.  The buffer is a flat list of dicts;
# list.append is atomic under the GIL, so writer threads need no lock.
# ``_active`` is ``_enabled or (flight-recorder ring attached)``: the
# monitor's always-on crash ring (observe/monitor.py) receives every
# record regardless of enable(), so instrumentation keeps feeding the
# forensic buffer even when full tracing is off.
_enabled = False
_active = False
_ring = None  # deque(maxlen=N) owned by monitor.FlightRecorder
_clock = time.perf_counter
_events: list = []
_dropped = 0
_max_events = 1_000_000  # hard cap: a forgotten enable() cannot OOM
_tls = threading.local()


def _update_active():
    global _active
    _active = _enabled or _ring is not None


def _attach_ring(ring):
    """Internal (monitor.FlightRecorder): route every emitted record
    into ``ring`` (an append-only bounded buffer, e.g. a deque with
    maxlen) in ADDITION to the main buffer; ``None`` detaches."""
    global _ring
    _ring = ring
    _update_active()


def enable(clock=None):
    """Turn tracing on.  ``clock``: ``() -> float`` seconds (default
    ``time.perf_counter``); inject a fake for deterministic tests."""
    global _enabled, _clock
    if clock is not None:
        _clock = clock
    _enabled = True
    _update_active()


def disable():
    """Turn tracing off (buffer retained — export then ``clear()``)
    and restore the default clock."""
    global _enabled, _clock
    _enabled = False
    _clock = time.perf_counter
    _update_active()


def is_enabled() -> bool:
    return _enabled


def clear():
    """Drop all buffered events (and the drop counter)."""
    drain()


def events() -> list:
    """Snapshot copy of the buffered events (safe while tracing)."""
    return list(_events)


def drain() -> list:
    """Return the buffered events and clear the buffer.  The buffer is
    SWAPPED (one rebind), not copied-then-deleted: a writer thread
    racing the drain lands its event either in the returned list or in
    the fresh buffer — never silently dropped."""
    global _events, _dropped
    out, _events = _events, []
    _dropped = 0
    return out


def dropped() -> int:
    """Events discarded because the buffer hit ``set_max_events``."""
    return _dropped


def set_max_events(n: int):
    """Resize the buffer cap (default 1e6 events)."""
    global _max_events
    if n < 1:
        raise ValueError(f"max_events must be >= 1, got {n}")
    _max_events = int(n)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _emit(rec: dict):
    global _dropped
    if _ring is not None:
        _ring.append(rec)  # bounded by construction (deque maxlen)
    if not _enabled:
        return
    if len(_events) >= _max_events:
        _dropped += 1
        return
    _events.append(rec)


class _NullSpan:
    """The shared disabled-mode span: enters/exits/sets for free.
    ``span()`` returns THIS object (no allocation) when tracing is
    off — the identity is part of the overhead contract."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def set(self, **args):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_t0", "_parent", "_depth",
                 "_clk")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args or None

    def set(self, **args):
        """Attach/overwrite span args mid-flight (e.g. a compile's
        cost-table numbers discovered inside the span)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)
        return self

    def __enter__(self):
        st = _stack()
        self._parent = st[-1] if st else None
        self._depth = len(st)
        st.append(self.name)
        self._clk = _clock
        self._t0 = _clock()
        return self

    def __exit__(self, *a):
        t1 = _clock()
        st = _stack()
        if st and st[-1] == self.name:
            st.pop()
        if not _active or _clock is not self._clk:
            # tracing AND recorder off, or enable()/disable() swapped
            # the clock mid-span: in the latter case the duration
            # would mix two time bases (garbage — possibly negative
            # billions of seconds), and no buffer, ring included, may
            # ever receive such a record
            return False
        _emit({"name": self.name, "cat": self.cat, "ph": "X",
               "ts": self._t0, "dur": t1 - self._t0,
               "tid": threading.current_thread().name,
               "depth": self._depth, "parent": self._parent,
               "args": self.args})
        return False


def span(name: str, cat: str = "app", **args):
    """Context manager timing one scope.  ``cat`` groups spans into
    one exporter track per subsystem (train/serve/comms/snapshot/...);
    keyword args become Chrome-trace span args."""
    if not _active:
        return _NULL_SPAN
    return _Span(name, cat, args)


def event(name: str, cat: str = "app", **args):
    """Zero-duration instant (Chrome "i" phase) — cache misses,
    collective issues, admissions."""
    if not _active:
        return
    st = _stack()
    _emit({"name": name, "cat": cat, "ph": "i", "ts": _clock(),
           "tid": threading.current_thread().name,
           "depth": len(st), "parent": st[-1] if st else None,
           "args": args or None})


def traced(fn=None, *, name=None, cat="app"):
    """Decorator form of ``span``: ``@traced`` or
    ``@traced(name="serve/prefill", cat="serve")``.  Disabled-mode
    cost is the one flag check."""
    if fn is None:
        return functools.partial(traced, name=name, cat=cat)
    label = name or getattr(fn, "__qualname__", fn.__name__)

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not _active:
            return fn(*a, **kw)
        with _Span(label, cat, None):
            return fn(*a, **kw)

    return wrapper
