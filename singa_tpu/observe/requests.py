"""Per-request lifecycle ledger — tail-latency attribution for the
serve stack (the request-tracing round).

The span layer (``trace.py``) answers "where did this STEP's time go";
nothing answers "where did this REQUEST's time go".  When TTFT p99
blows an SLO on a fleet, the existing telemetry can say *that* it
regressed but not *which* requests were slow or *why* — queue wait
behind a burst, a cold prefill that a warm prefix hit would have
skipped, a speculative chunk whose acceptance collapsed, or a failover
hop that restarted the wait from zero.  This module records ONE
structured timeline per ``GenerationRequest.request_id``:

* **hops** — every engine submission is a hop.  The initial submit is
  hop 0; a supervisor restart's requeue, a fleet failover's requeue,
  and a hedge re-dispatch each append another.  The SAME entry follows
  the request across replicas (resurrection: a rejected-requeue-safe
  entry reopens when the request is resubmitted), so a
  failover-requeued request's ledger shows both replicas with the time
  burned on each.
* **events per hop** — submit, queue position at enqueue, admission
  (cold vs prefix-warm with the hit-token count), each warm prefill
  chunk, first token, each decode/spec step with accepted-token
  counts, and typed rejections (shed / deadline / queue-full / engine
  failure / abandon), fed by narrow hooks in ``serve/engine.py``,
  ``serve/scheduler.py``, ``serve/prefix.py``, ``serve/supervisor.py``
  and ``serve/fleet.py``.
* **phase attribution** — at retire the timeline is decomposed into
  ``hops`` (time burned on earlier hops before the final submission),
  ``ship`` (a disaggregated admission's KV transfer — the fleet
  stamps the final ``via=kv_ship`` hop with its measured ``ship_s``,
  carved OUT of ``hops`` so a slow interconnect reads "ship", not
  "hops"), ``queue`` (final-hop submit → admission), ``prefill``
  (admission → first token), ``decode`` (first token → retire, stall
  and preemption removed), ``stall`` (inter-token gaps far beyond the
  request's own median — the spec-verify / scheduler-starvation
  signature) and ``preempted`` (time the paged engine held the
  request swapped out to host; swap pauses are excluded from the
  stall detector's gaps so the two phases never double-count one
  pause).  ``hops + ship + queue + prefill`` sum to TTFT *exactly*
  and all seven sum to the request's total latency exactly —
  attribution is arithmetic over recorded timestamps, never an
  estimate.
* **bounded retention** — sealed (retired or terminally rejected)
  entries live in a ring of ``capacity`` entries (the FlightRecorder
  idiom: a forgotten ledger cannot OOM), exported as strict JSONL via
  :func:`write_request_log` and as per-request Chrome-trace tracks
  (``export.request_trace_events``, flow arrows linking hops).

Disabled-mode contract (the ``trace._active`` discipline): every hook
site reads ONE module flag (``requests._active``) and allocates
nothing when it is False.  The ledger is pure host bookkeeping — no
jax, nothing enters jitted code, so the serve engine's
no-runtime-recompiles pin holds with the ledger on
(``bench_serve.py --request-log`` gates it).

The one-call summary is :func:`why_slow_section` —
``health_report()["serve"]["why_slow"]`` decomposes the top-K slowest
requests and the TTFT/TPOT p99 population into phase components, so
"p99 regressed" becomes "p99 is 80% queue wait on replica 1".

Usage::

    from singa_tpu.observe import requests as reqtrace
    reqtrace.enable(capacity=1024)
    ... serve traffic ...
    reqtrace.write_request_log("/tmp/requests.jsonl")
    print(reqtrace.why_slow_section()["ttft_p99_attribution"])
    reqtrace.disable()
"""

from __future__ import annotations

import json

from ..utils.metrics import percentile

__all__ = ["RequestLedger", "enable", "disable", "active", "ledger",
           "why_slow_section", "write_request_log",
           "set_host_namer"]

# Module-global fast path, mirroring trace._active: `if not
# requests._active: <skip>` is the ENTIRE disabled cost of a hook
# site.  _ledger is non-None exactly while _active is True.
_active = False
_ledger = None

#: outcomes that mean "completed normally" (engine finish reasons —
#: "pruned" is a fork branch cut on purpose, a sealed result, not a
#: rejection)
_COMPLETED = ("length", "stop", "pruned")

# replica index -> host id, installed by a DistFleet (observe.federate)
# so hop records carry WHERE a hop ran across the process boundary;
# None (the default) leaves hosts unset — in-process fleets group
# under "local" in the per-host attribution
_host_namer = None


def set_host_namer(fn):
    """Install (or clear, with None) the replica->host-id mapping the
    ledger stamps onto hops as ``replica`` annotations arrive.  The
    dist fleet owns this: ``w<idx>`` per worker peer."""
    global _host_namer
    _host_namer = fn


def enable(capacity=1024, record_steps=True) -> "RequestLedger":
    """Attach a fresh process-wide ledger and turn the hooks on.
    ``capacity`` bounds the sealed-entry ring; ``record_steps=False``
    keeps only per-hop token counts instead of per-step timestamps
    (cheaper, but disables stall attribution)."""
    global _active, _ledger
    _ledger = RequestLedger(capacity=capacity,
                            record_steps=record_steps)
    _active = True
    return _ledger


def disable():
    """Detach the ledger and turn the hooks off.  The previously
    returned ledger object stays readable (export after disable); new
    serve activity no longer reaches it."""
    global _active, _ledger
    _active = False
    _ledger = None


def active() -> bool:
    return _active


def ledger():
    """The live ledger, or None when tracing is off."""
    return _ledger


def why_slow_section(top_k=5) -> dict:
    """The ``health_report()["serve"]["why_slow"]`` section: always a
    dict with an ``enabled`` key, so dashboards and the CI gate can
    assert on it unconditionally."""
    if not _active or _ledger is None:
        return {"enabled": False}
    return _ledger.why_slow(top_k=top_k)


def write_request_log(path, ledger_=None) -> int:
    """Write the sealed-entry ring as strict JSONL (one request per
    line, ``json_sanitize``-d: nan/inf become null); returns the line
    count.  Defaults to the live ledger."""
    lg = ledger_ if ledger_ is not None else _ledger
    if lg is None:
        raise RuntimeError(
            "no request ledger: call requests.enable() first (or pass "
            "one explicitly)")
    n = 0
    with open(path, "w") as f:
        for line in lg.jsonl_lines():
            f.write(line + "\n")
            n += 1
    return n


def _final_hop(e):
    """The hop whose engine actually served the request: latest hop
    with a first token (a requeue's earlier hops never got one), else
    the latest hop (never-admitted rejections)."""
    for h in reversed(e["hops"]):
        if h.get("t_first_token") is not None:
            return h
    return e["hops"][-1]


def _new_hop(engine, t):
    return {
        "engine": engine,       # EngineStats.engine_label (unique)
        "replica": None,        # fleet replica index, when routed
        "host": None,           # host id, when served across the
        #                         process boundary (observe.federate)
        "via": "submit",        # submit|supervisor_restart|failover|
        #                         hedge|refused|prefill|kv_ship|
        #                         ship_fallback
        "t_submit": t,
        "queue_depth_at_enqueue": None,
        "t_admit": None,
        "admit_kind": None,     # cold | warm
        "hit_tokens": 0,
        "slot": None,
        "branch": None,         # fork branch index (serve/fork.py);
        #                         None outside a fork family
        "chunks": [],           # [t, offset] per warm prefill chunk
        "t_first_token": None,
        "steps": [],            # [t, tokens] or [t, tokens, acc, drafted]
        "tokens": 0,            # tokens emitted on THIS hop
        "preemptions": [],      # [t_swap_out, t_swap_in|None] pairs
        "reject": None,         # {"t", "reason", "started"} terminal
    }


class RequestLedger:
    """Hook sink + bounded store for per-request timelines.

    Single-writer by design (the serve loop is single-threaded; dict/
    list mutation is GIL-atomic for the read paths).  Every hook is
    no-throw for unknown request ids — a telemetry layer must never be
    able to fail a request it is describing."""

    def __init__(self, capacity=1024, record_steps=True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.record_steps = bool(record_steps)
        self._open = {}            # rid -> entry (unresolved)
        self._ring = []            # sealed entries, oldest first
        self._sealed_by_rid = {}   # rid -> latest sealed entry
        self.dropped = 0           # sealed entries evicted by the cap

    # -- internals -------------------------------------------------------
    def _hop(self, rid, engine=None):
        """The hop engine-side events land on: the entry's latest hop
        whose engine label matches (hedged twins run concurrently on
        two engines), else the latest hop.  Falls back to the sealed
        entry so a step/retire racing a seal (a speculative chunk's
        trailing step record) still lands."""
        e = self._open.get(rid)
        if e is None:
            e = self._sealed_by_rid.get(rid)
        if e is None or not e["hops"]:
            return None, None
        if engine is not None:
            for h in reversed(e["hops"]):
                if h["engine"] == engine:
                    return e, h
        return e, e["hops"][-1]

    def _seal(self, e):
        rid = e["request_id"]
        self._open.pop(rid, None)
        self._ring.append(e)
        self._sealed_by_rid[rid] = e
        while len(self._ring) > self.capacity:
            old = self._ring.pop(0)
            self.dropped += 1
            if self._sealed_by_rid.get(old["request_id"]) is old:
                del self._sealed_by_rid[old["request_id"]]

    # -- hooks (serve layer) ---------------------------------------------
    def on_submit(self, rid, engine, t, prompt_len=None,
                  max_new_tokens=None):
        """An engine accepted a submission: start a hop.  A request id
        already open gets a concurrent hop (hedge); a sealed entry
        whose rejection was requeue-safe (``started is not True``) is
        RESURRECTED — the same timeline continues across supervisor
        restarts and fleet failovers.  A completed entry's id starts a
        fresh timeline (the engine allows id reuse after resolution)."""
        hop = _new_hop(engine, t)
        e = self._open.get(rid)
        if e is not None:
            e["hops"].append(hop)
            return
        e = self._sealed_by_rid.get(rid)
        if (e is not None and e["outcome"] == "rejected"
                and e.get("started") is not True):
            # requeue: reopen the SAME entry — hop continuity is the
            # point of the ledger
            try:
                self._ring.remove(e)
            except ValueError:
                pass
            del self._sealed_by_rid[rid]
            e["outcome"] = e["reason"] = None
            e["started"] = None
            e["t_retire"] = None
            e["ttft_s"] = e["tpot_s"] = None
            e["phases"] = None
            e.pop("final_hop", None)
            e["hops"].append(hop)
            self._open[rid] = e
            return
        self._open[rid] = {
            "request_id": rid,
            "prompt_len": prompt_len,
            "max_new_tokens": max_new_tokens,
            "t_submit": t,
            "t_retire": None,
            "outcome": None,       # length|stop|rejected
            "reason": None,
            "started": None,       # last rejection's started flag
            "tokens_out": 0,
            "ttft_s": None,
            "tpot_s": None,
            "phases": None,
            "hops": [hop],
        }

    def annotate_hop(self, rid, engine=None, **attrs):
        """Attach routing metadata to the latest hop (the fleet sets
        ``replica``/``via``, the scheduler the enqueue depth)."""
        _, hop = self._hop(rid, engine)
        if hop is not None:
            hop.update(attrs)
            if _host_namer is not None and hop.get("host") is None \
                    and hop.get("replica") is not None:
                hop["host"] = _host_namer(hop["replica"])

    def on_admit(self, rid, engine, t, slot=None, step=None,
                 branch=None):
        """Admission started: the request left the queue for a pool
        slot (cold/warm classification arrives from the prefix cache's
        hook; no cache means it stays the cold default).  ``branch``:
        the fork branch index for a branch spawned off a live sibling
        (serve/fork.py) — its hop has zero queue and prefill by
        construction, and the branch id keeps the family legible in
        why_slow rows."""
        _, hop = self._hop(rid, engine)
        if hop is not None:
            hop["t_admit"] = t
            hop["slot"] = slot
            if branch is not None:
                hop["branch"] = int(branch)
            if hop["admit_kind"] is None:
                hop["admit_kind"] = "cold"

    def on_prefix(self, rid, hit_tokens):
        """Prefix-cache admission accounting (PrefixCache.on_admit):
        the warm/cold verdict and how many prompt tokens came from
        cached blocks."""
        _, hop = self._hop(rid)
        if hop is not None:
            hop["admit_kind"] = "warm" if hit_tokens > 0 else "cold"
            hop["hit_tokens"] = int(hit_tokens)

    def on_prefill_chunk(self, rid, engine, t, offset):
        """One block-width warm prefill window finished."""
        _, hop = self._hop(rid, engine)
        if hop is not None:
            hop["chunks"].append([t, int(offset)])

    def on_first_token(self, rid, engine, t):
        _, hop = self._hop(rid, engine)
        if hop is not None:
            hop["t_first_token"] = t
            hop["tokens"] += 1

    def on_step(self, rid, engine, t, tokens, accepted=None,
                drafted=None):
        """One engine step's emissions for this request: ``tokens``
        actually emitted (1 on a plain engine; up to spec_k on a
        speculative one), with the chunk's accepted/drafted proposal
        counts when speculating."""
        _, hop = self._hop(rid, engine)
        if hop is None:
            return
        hop["tokens"] += int(tokens)
        if self.record_steps:
            rec = [t, int(tokens)]
            if accepted is not None:
                rec += [int(accepted), int(drafted)]
            hop["steps"].append(rec)

    def on_preempt(self, rid, engine, t):
        """The paged engine swapped this request's blocks to host
        mid-decode: open a preemption interval on the hop.  Time
        inside it is attributed to the ``preempted`` phase at seal
        (exact arithmetic — carved OUT of decode, and excluded from
        the stall detector's inter-step gaps so the two phases never
        double-count one pause)."""
        _, hop = self._hop(rid, engine)
        if hop is not None:
            hop.setdefault("preemptions", []).append([t, None])

    def on_resume(self, rid, engine, t):
        """The request's blocks were restored and decode continues:
        close the newest open preemption interval."""
        _, hop = self._hop(rid, engine)
        if hop is None:
            return
        for iv in reversed(hop.get("preemptions") or []):
            if iv[1] is None:
                iv[1] = t
                break

    def on_retire(self, rid, engine, t, finish_reason, tokens=None):
        """Normal completion: seal the entry with its phase
        attribution.  Idempotent against hedge losers — a second
        retire for an already-completed id only annotates the losing
        hop."""
        e, hop = self._hop(rid, engine)
        if e is None:
            return
        if e["outcome"] in _COMPLETED:
            if hop is not None:
                hop["duplicate_retire_t"] = t
            return
        e["outcome"] = finish_reason
        e["t_retire"] = t
        if tokens is not None:
            e["tokens_out"] = int(tokens)
        # the hop the retiring ENGINE matched is authoritative: on a
        # hedged request the last-by-position hop may be the losing
        # twin, whose timestamps must not define ttft/tpot
        self._finalize(e, final=(hop if hop is not None
                                 and hop.get("t_first_token")
                                 is not None else None))
        self._seal(e)

    def on_reject(self, rid, t, reason, engine=None, started=None,
                  prompt_len=None, max_new_tokens=None):
        """Typed rejection: record a terminal hop event and seal.
        ``started`` keeps the engine's re-runnability verdict — a
        later resubmission of a ``started is not True`` entry reopens
        it (requeue continuity).  Unknown ids get a minimal sealed
        entry (a request refused before any engine accepted it —
        SLO-pressure admission, fleet down — must still appear in the
        request log instead of vanishing)."""
        e, hop = self._hop(rid, engine)
        if e is None:
            hop = _new_hop(None, t)
            hop["via"] = "refused"
            e = {
                "request_id": rid, "prompt_len": prompt_len,
                "max_new_tokens": max_new_tokens, "t_submit": t,
                "t_retire": None, "outcome": None, "reason": None,
                "started": None, "tokens_out": 0, "ttft_s": None,
                "tpot_s": None, "phases": None, "hops": [hop],
            }
            self._open[rid] = e
        if hop is not None and hop["reject"] is None:
            hop["reject"] = {"t": t, "reason": reason,
                             "started": started}
        if e["outcome"] in _COMPLETED:
            return  # hedge loser rejected after the winner completed
        e["reason"] = reason if e["reason"] is None \
            else f'{e["reason"]}; {reason}'
        e["started"] = started if started is not None else e["started"]
        if e["outcome"] == "rejected":
            return  # already sealed; reason/event updated above
        e["outcome"] = "rejected"
        e["t_retire"] = t
        self._finalize(e)
        self._seal(e)

    # -- attribution -----------------------------------------------------
    @staticmethod
    def _phases(e, final=None) -> dict:
        """Decompose one entry into the phase components (module
        docstring).  Exact by construction: hops + ship + queue +
        prefill == TTFT and all seven sum to t_retire - t_submit
        (stall is carved OUT of decode and ship OUT of hops, never
        added on top)."""
        if final is None:
            final = _final_hop(e)
        end = e["t_retire"] if e["t_retire"] is not None \
            else final["t_submit"]
        hops_s = max(final["t_submit"] - e["t_submit"], 0.0)
        # a disaggregated admission's KV transfer: the fleet stamps
        # the via=kv_ship hop with its measured ship_s (export ->
        # validate -> scatter), which happened strictly BEFORE this
        # hop's submit — carve it out of the hops span so the sums
        # stay exact and a slow ship is named, not lumped into "hops"
        ship_s = min(float(final.get("ship_s") or 0.0), hops_s)
        hops_s -= ship_s
        t_admit = final.get("t_admit")
        t_first = final.get("t_first_token")
        if t_admit is not None:
            queue_s = max(t_admit - final["t_submit"], 0.0)
        else:
            # never admitted on the final hop (rejected in queue)
            queue_s = max(end - final["t_submit"], 0.0)
        prefill_s = (max(t_first - t_admit, 0.0)
                     if t_first is not None and t_admit is not None
                     else 0.0)
        decode_s = (max(end - t_first, 0.0)
                    if t_first is not None else 0.0)
        # preempted: time the paged engine held this request swapped
        # out (clipped to the decode span — preemption only exists
        # after the first token, since admission always emits one)
        ivs = []
        for t_out, t_in in final.get("preemptions") or []:
            t_in = end if t_in is None else t_in
            if t_first is not None:
                a, b = max(t_out, t_first), min(t_in, end)
                if b > a:
                    ivs.append((a, b))
        preempted_s = min(sum(b - a for a, b in ivs), decode_s)
        stall_s = 0.0
        steps = final.get("steps") or []
        ts = [s[0] for s in steps]

        def swapped_inside(a, b):
            return sum(max(0.0, min(b, ti) - max(a, to))
                       for to, ti in ivs)

        # inter-step gaps NET of preemption time inside them: a swap
        # pause is the preempted phase's, never double-counted as
        # stall
        gaps = [b - a - swapped_inside(a, b)
                for a, b in zip(ts, ts[1:])]
        if len(gaps) >= 3:
            med = sorted(gaps)[len(gaps) // 2]
            if med > 0:
                # a gap 3x the request's own median inter-step time is
                # a stall (scheduler starvation, a slow spec verify, a
                # straggler compile) — subtract the excess over the
                # median so phase sums stay exact
                stall_s = sum(g - med for g in gaps if g > 3 * med)
        stall_s = min(stall_s, decode_s - preempted_s)
        return {
            "hops": hops_s,
            "ship": ship_s,
            "queue": queue_s,
            "prefill": prefill_s,
            "decode": decode_s - stall_s - preempted_s,
            "stall": stall_s,
            "preempted": preempted_s,
        }

    def _finalize(self, e, final=None):
        """Compute the derived latency fields at seal time so every
        JSONL line is self-contained.  ``final``: the hop that
        actually served the request (on_retire passes the engine-
        matched hop — on a hedged request the last hop by position
        may be the losing twin); falls back to the latest hop with a
        first token."""
        if final is None:
            final = _final_hop(e)
        e["final_hop"] = e["hops"].index(final)
        if final.get("t_first_token") is not None:
            e["ttft_s"] = final["t_first_token"] - e["t_submit"]
            # tokens_out (the engine's count at retire) over the hop's
            # own tally: the final step's on_step record can land
            # AFTER retire seals the entry (the engine emits, retires,
            # then writes the step record), so the hop tally may lag
            # by the last step's tokens at this point
            n = e["tokens_out"] or final["tokens"]
            if (e["t_retire"] is not None and n > 1):
                e["tpot_s"] = ((e["t_retire"] - final["t_first_token"])
                               / (n - 1))
        e["phases"] = self._phases(e, final)

    # -- reads -----------------------------------------------------------
    def entries(self) -> list:
        """Snapshot copy of the sealed ring, oldest first."""
        return list(self._ring)

    def entry(self, rid):
        """The entry for ``rid`` — open, else latest sealed, else
        None."""
        return self._open.get(rid) or self._sealed_by_rid.get(rid)

    @property
    def open_count(self) -> int:
        return len(self._open)

    def jsonl_lines(self):
        """One strict-JSON line per sealed entry (nan/inf -> null, the
        same json_sanitize contract the benches use)."""
        from .export import json_sanitize
        for e in self._ring:
            yield json.dumps(json_sanitize(e), default=str,
                             allow_nan=False)

    @staticmethod
    def _replica_key(e) -> str:
        """Grouping key for per-replica attribution: the final hop's
        fleet replica index when routed, else its engine label.  Uses
        the seal-time ``final_hop`` verdict (the hop whose engine
        retired the request) when present."""
        idx = e.get("final_hop")
        final = (e["hops"][idx] if idx is not None
                 else _final_hop(e))
        if final.get("replica") is not None:
            return str(final["replica"])
        return f'engine:{final.get("engine")}'

    @staticmethod
    def _host_key(e) -> str:
        """Grouping key for per-host attribution: the final hop's host
        id when served across the process boundary (observe.federate
        stamps it), else "local" — an in-process fleet is one host."""
        idx = e.get("final_hop")
        final = (e["hops"][idx] if idx is not None
                 else _final_hop(e))
        return final.get("host") or "local"

    def why_slow(self, top_k=5) -> dict:
        """Tail-latency attribution over the sealed ring.

        ``ttft_p99_attribution``: for the requests at/above the TTFT
        p99 (nearest-rank — the actual slowest observed requests),
        each phase's share of their summed TTFT; the fractions sum to
        1.  ``per_replica`` splits the same population by where the
        request finally ran.  ``tpot_p99_attribution`` does the decode
        side: how much of the slow requests' decode span was stall.
        ``slowest`` is the per-request evidence: top-K by TTFT with
        full phase breakdowns and the hop chain."""
        completed = [e for e in self._ring
                     if e["outcome"] in _COMPLETED
                     and e["ttft_s"] is not None]
        rejected = sum(1 for e in self._ring
                       if e["outcome"] == "rejected")
        out = {
            "enabled": True,
            "requests_tracked": len(self._ring),
            "open_requests": len(self._open),
            "completed": len(completed),
            "rejected": rejected,
            "dropped": self.dropped,
            "ttft_p99_s": None,
            "ttft_p99_attribution": {},
            "latency_p99_attribution": {},
            "per_replica": {},
            "per_host": {},
            "straggler_host": None,
            "tpot_p99_s": None,
            "tpot_p99_attribution": {},
            "slowest": [],
        }
        if not completed:
            return out
        ttfts = [e["ttft_s"] for e in completed]
        p99 = percentile(ttfts, 99)
        out["ttft_p99_s"] = p99
        pop = [e for e in completed if e["ttft_s"] >= p99]
        total = sum(e["ttft_s"] for e in pop)
        sums = {"queue": 0.0, "prefill": 0.0, "hops": 0.0,
                "ship": 0.0}
        # the full end-to-end decomposition over the same population:
        # all SEVEN phases sum to t_retire - t_submit per entry
        # (_phases is exact by construction), so these fractions sum
        # to 1 — the fleet-level "where did the whole latency go"
        lat_total = sum(e["t_retire"] - e["t_submit"] for e in pop)
        lat_sums = {"queue": 0.0, "prefill": 0.0, "ship": 0.0,
                    "decode": 0.0, "stall": 0.0, "preempted": 0.0,
                    "hops": 0.0}
        per_rep, per_host = {}, {}
        for e in pop:
            ph = e["phases"] or self._phases(e)
            for k in sums:
                sums[k] += ph.get(k, 0.0)
            for k in lat_sums:
                lat_sums[k] += ph.get(k, 0.0)
            rep = per_rep.setdefault(self._replica_key(e), {
                "requests": 0, "ttft_s": 0.0, "queue": 0.0,
                "prefill": 0.0, "hops": 0.0, "ship": 0.0})
            rep["requests"] += 1
            rep["ttft_s"] += e["ttft_s"]
            for k in ("queue", "prefill", "hops", "ship"):
                rep[k] += ph.get(k, 0.0)
            hst = per_host.setdefault(self._host_key(e), {
                "requests": 0, "ttft_s": 0.0, "total_s": 0.0})
            hst["requests"] += 1
            hst["ttft_s"] += e["ttft_s"]
            hst["total_s"] += e["t_retire"] - e["t_submit"]
        out["ttft_p99_attribution"] = {
            k: {"s": v, "frac": (v / total if total > 0 else 0.0)}
            for k, v in sums.items()}
        out["latency_p99_attribution"] = {
            k: {"s": v,
                "frac": (v / lat_total if lat_total > 0 else 0.0)}
            for k, v in lat_sums.items()}
        out["per_replica"] = per_rep
        out["per_host"] = per_host
        # the straggler: the host contributing the most tail TTFT —
        # same max-by idiom as health's step-time straggler
        worst = max(per_host, key=lambda h: per_host[h]["ttft_s"])
        out["straggler_host"] = {
            "host": worst, "ttft_s": per_host[worst]["ttft_s"],
            "requests": per_host[worst]["requests"]}
        tpots = [e["tpot_s"] for e in completed
                 if e["tpot_s"] is not None]
        if tpots:
            tp99 = percentile(tpots, 99)
            out["tpot_p99_s"] = tp99
            dpop = [e for e in completed
                    if e["tpot_s"] is not None and e["tpot_s"] >= tp99]
            dec = sum((e["phases"] or {}).get("decode", 0.0)
                      for e in dpop)
            stall = sum((e["phases"] or {}).get("stall", 0.0)
                        for e in dpop)
            pre = sum((e["phases"] or {}).get("preempted", 0.0)
                      for e in dpop)
            dt = dec + stall + pre
            out["tpot_p99_attribution"] = {
                "decode": {"s": dec,
                           "frac": dec / dt if dt > 0 else 0.0},
                "stall": {"s": stall,
                          "frac": stall / dt if dt > 0 else 0.0},
                # the paged engine's swap time: a slow request that
                # spent its tail preempted reads "preempted", not
                # "decode got slow"
                "preempted": {"s": pre,
                              "frac": pre / dt if dt > 0 else 0.0},
            }
        for e in sorted(completed, key=lambda e: -e["ttft_s"])[:top_k]:
            ph = e["phases"] or self._phases(e)
            out["slowest"].append({
                "request_id": e["request_id"],
                "ttft_s": e["ttft_s"],
                "total_s": (e["t_retire"] - e["t_submit"]
                            if e["t_retire"] is not None else None),
                "tokens_out": e["tokens_out"],
                "phases": ph,
                "dominant_phase": max(ph, key=ph.get),
                "hops": [{"engine": h.get("engine"),
                          "replica": h.get("replica"),
                          "host": h.get("host"),
                          "via": h.get("via"),
                          "branch": h.get("branch")}
                         for h in e["hops"]],
            })
        return out

    def snapshot(self) -> dict:
        """Small JSON-able status block (health/debugging)."""
        return {
            "capacity": self.capacity,
            "sealed": len(self._ring),
            "open": len(self._open),
            "dropped": self.dropped,
        }
