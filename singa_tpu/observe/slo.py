"""Multi-window SLO burn-rate alerting (the Google-SRE workbook
pattern) over the windowed telemetry layer.

The passive half already exists: every retire is checked against the
declared :class:`~singa_tpu.observe.health.SLO` and breaches count
into ``serve.slo_violations{engine=,kind=}``.  This module is the
ACTIVE half — it answers "how fast is the error budget burning, and
is that page-worthy":

    burn_rate(window) = (violations/sec over window
                         / completions/sec over window) / budget_frac

A burn rate of 1 spends exactly the error budget (``budget_frac`` of
requests may violate); 14 spends a 30-day budget in ~2 days.  Each
:class:`BurnRule` pairs a LONG window (is this real?) with a SHORT
window (is it still happening?) and fires only when BOTH burn above
its threshold — the standard defense against paging on a blip and
against paging forever after a burst ends.  Alerts clear
HYSTERETICALLY: both windows must fall below
``threshold * clear_ratio`` before the alert clears, so a burn
hovering at the threshold doesn't flap.

Surfaces (all add-only):

* ``serve.slo.burn_rate{window=60}`` gauges — one per distinct window,
  refreshed on every :meth:`SLOPolicy.poll`;
* ``serve.slo.alert_firing{rule=page}`` gauges (0/1) and
  ``serve.slo.alerts_fired/alerts_cleared{rule=}`` counters;
* ``serve/slo_alert`` trace instants on every fire/clear (captured by
  the flight recorder even with tracing off);
* ``health_report()["serve"]["slo_alerts"]`` — always present,
  ``{"enabled": False}`` until a policy is installed;
* an ``on_alert(rule_name, firing, info)`` callback hook — the fleet
  autoscaler (serve/autoscale.py) subscribes here, and so can a pager.

Polling is THREADLESS by design (the ``Watchdog.check()`` idiom): the
owner calls :meth:`poll` from its drive loop with an injectable clock,
so every transition is deterministic under test.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from . import trace as _trace
from .registry import registry as _registry
from .timeseries import _wlabel

__all__ = ["BurnRule", "SLOPolicy", "DEFAULT_RULES", "install",
           "uninstall", "installed", "alerts_section"]


@dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate alert.

    ``threshold`` is the burn-rate multiple both windows must exceed
    to fire; ``clear_ratio`` (in (0, 1]) scales it down for the clear
    condition (hysteresis).  The defaults below mirror the SRE
    workbook's page/ticket split, scaled to this layer's default
    window ladder."""

    name: str
    long_s: float
    short_s: float
    threshold: float
    clear_ratio: float = 0.8

    def validate(self):
        if not self.name:
            raise ValueError("BurnRule needs a name")
        if self.short_s <= 0 or self.long_s <= 0 \
                or self.short_s >= self.long_s:
            raise ValueError(
                f"BurnRule {self.name!r}: need 0 < short_s < long_s, "
                f"got short={self.short_s} long={self.long_s}")
        if self.threshold <= 0:
            raise ValueError(
                f"BurnRule {self.name!r}: threshold must be > 0, got "
                f"{self.threshold}")
        if not 0.0 < self.clear_ratio <= 1.0:
            raise ValueError(
                f"BurnRule {self.name!r}: clear_ratio must be in "
                f"(0, 1], got {self.clear_ratio}")


#: fast page (1m/5m) + slow ticket (30m/1h): the workbook pairing.
DEFAULT_RULES = (
    BurnRule("page", long_s=300.0, short_s=60.0, threshold=14.4),
    BurnRule("ticket", long_s=3600.0, short_s=1800.0, threshold=3.0),
)

# the installed policy (None = feature off): health_report reads the
# section through module functions so observe.health never imports a
# policy instance directly
_policy = None


def install(policy):
    """Make ``policy`` the process-wide one (last install wins — the
    health report shows one policy, like the watchdog)."""
    global _policy
    _policy = policy
    return policy


def uninstall(policy=None):
    """Detach the installed policy (or only ``policy`` if given and
    it is the installed one)."""
    global _policy
    if policy is None or _policy is policy:
        _policy = None


def installed():
    return _policy


def alerts_section() -> dict:
    """The ``health_report()["serve"]["slo_alerts"]`` section: always
    a dict with an ``enabled`` key so dashboards and CI can assert on
    it unconditionally."""
    if _policy is None:
        return {"enabled": False}
    return _policy.section()


class SLOPolicy:
    """Turn the per-retire violation counters into multi-window
    burn-rate alerts.

    >>> policy = observe.slo.SLOPolicy(slo, budget_frac=0.01)
    >>> while serving:
    ...     fleet.step()
    ...     policy.poll()          # threadless; injectable clock

    ``slo`` is the same object handed to ``model.serve(slo=...)`` —
    the policy never re-checks targets, it consumes the counters the
    engines already emit (``serve.slo_violations``) against the
    completion counters (``serve.completed``), summed across engines:
    a fleet burns ONE budget.  ``kinds`` restricts which violation
    kinds count as budget spend (default: the per-request kinds;
    ``queue`` violations are per scheduling pass, a different
    denominator).  ``budget_frac`` is the error budget as a fraction
    of requests (0.01 = 99% objective).

    ``install=True`` (default) registers the policy as the process
    policy so it surfaces in ``health_report()``; :meth:`close`
    unregisters the gauges and uninstalls."""

    def __init__(self, slo=None, budget_frac=0.01,
                 rules=DEFAULT_RULES, kinds=("ttft", "tpot"),
                 reg=None, clock=time.monotonic, on_alert=None,
                 ring_capacity=None, install=True):
        if not 0.0 < budget_frac < 1.0:
            raise ValueError(
                f"budget_frac must be in (0, 1), got {budget_frac}")
        rules = tuple(rules)
        if not rules:
            raise ValueError("need at least one BurnRule")
        for r in rules:
            r.validate()
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")
        self.slo = slo
        self.budget_frac = float(budget_frac)
        self.rules = rules
        self.kinds = tuple(kinds)
        self.clock = clock
        self.on_alert = on_alert
        reg = reg if reg is not None else _registry()
        self.registry = reg
        self.windows = tuple(sorted(
            {r.short_s for r in rules} | {r.long_s for r in rules}))
        wkw = {} if ring_capacity is None else {
            "capacity": ring_capacity}
        self._wf_viol = reg.windowed(
            "serve.slo_violations", windows=self.windows, clock=clock,
            **wkw)
        self._wf_done = reg.windowed(
            "serve.completed", windows=self.windows, clock=clock,
            **wkw)
        self._g_burn = {
            w: reg.gauge("serve.slo.burn_rate",
                         help="error-budget burn-rate multiple over "
                              "the window (1 = spending exactly the "
                              "budget)", window=_wlabel(w))
            for w in self.windows}
        self._g_firing, self._c_fired, self._c_cleared = {}, {}, {}
        for r in rules:
            self._g_firing[r.name] = reg.gauge(
                "serve.slo.alert_firing",
                help="1 while the burn-rate alert is firing",
                rule=r.name)
            self._c_fired[r.name] = reg.counter(
                "serve.slo.alerts_fired",
                help="burn-rate alert fire transitions", rule=r.name)
            self._c_cleared[r.name] = reg.counter(
                "serve.slo.alerts_cleared",
                help="burn-rate alert clear transitions", rule=r.name)
        self._registered = (list(self._g_burn.values())
                            + list(self._g_firing.values())
                            + list(self._c_fired.values())
                            + list(self._c_cleared.values()))
        # rule name -> state dict (the section()/autoscaler surface)
        self.alerts = {
            r.name: {"firing": False, "since": None,
                     "burn_short": 0.0, "burn_long": 0.0,
                     "fired": 0, "cleared": 0}
            for r in rules}
        self._burn_last = {w: 0.0 for w in self.windows}
        if install:
            globals()["install"](self)

    # -- arithmetic ------------------------------------------------------
    def error_ratio(self, window, now=None) -> float:
        """Violations / completions over the window, fleet-summed.
        0.0 when nothing completed AND nothing violated; inf when
        violations arrive while completions are zero (a wedged fleet
        is burning budget, not idling)."""
        if now is None:
            now = self.clock()
        bad = sum(
            self._wf_viol.rate(window, now, match={"kind": k})
            for k in self.kinds)
        good = self._wf_done.rate(window, now)
        if good <= 0.0:
            return 0.0 if bad <= 0.0 else float("inf")
        return bad / good

    def burn_rate(self, window, now=None) -> float:
        """Error ratio over the window as a multiple of the budget."""
        return self.error_ratio(window, now) / self.budget_frac

    # -- the poll loop ---------------------------------------------------
    def poll(self, now=None) -> dict:
        """Refresh burn gauges and drive every rule's fire/clear state
        machine; returns :meth:`section`.  Safe to call as often as
        the owner likes — transitions are edge-triggered."""
        if now is None:
            now = self.clock()
        burns = {}
        for w in self.windows:
            b = self.burn_rate(w, now)
            burns[w] = b
            self._burn_last[w] = b
            # inf is honest (violations with zero completions); the
            # JSON writers sanitize it to null, Prometheus to +Inf
            self._g_burn[w].set(b)
        for rule in self.rules:
            st = self.alerts[rule.name]
            b_s, b_l = burns[rule.short_s], burns[rule.long_s]
            st["burn_short"], st["burn_long"] = b_s, b_l
            if not st["firing"]:
                if b_s >= rule.threshold and b_l >= rule.threshold:
                    st["firing"] = True
                    st["since"] = now
                    st["fired"] += 1
                    self._c_fired[rule.name].inc()
                    self._g_firing[rule.name].set(1)
                    self._transition(rule, True, b_s, b_l)
            else:
                clear_at = rule.threshold * rule.clear_ratio
                if b_s <= clear_at and b_l <= clear_at:
                    st["firing"] = False
                    st["since"] = None
                    st["cleared"] += 1
                    self._c_cleared[rule.name].inc()
                    self._g_firing[rule.name].set(0)
                    self._transition(rule, False, b_s, b_l)
        return self.section(now)

    def _transition(self, rule, firing, b_s, b_l):
        info = {"rule": rule.name, "firing": firing,
                "burn_short": b_s, "burn_long": b_l,
                "threshold": rule.threshold,
                "short_s": rule.short_s, "long_s": rule.long_s,
                "budget_frac": self.budget_frac}
        _trace.event("serve/slo_alert", cat="serve", **info)
        if self.on_alert is not None:
            # a raising subscriber must not kill the poll loop — the
            # alert state is already committed; log and move on
            try:
                self.on_alert(rule.name, firing, info)
            except Exception:
                from ..utils.logging import get_channel
                get_channel("observe").exception(
                    "slo on_alert callback raised for %s", rule.name)

    def firing(self, rule_name=None) -> bool:
        """True when the named rule (or ANY rule) is firing."""
        if rule_name is not None:
            return self.alerts[rule_name]["firing"]
        return any(st["firing"] for st in self.alerts.values())

    def section(self, now=None) -> dict:
        """The health/SOAK view of the policy state (always JSON-able;
        inf burn rates sanitize to null on the wire)."""
        return {
            "enabled": True,
            "budget_frac": self.budget_frac,
            "kinds": list(self.kinds),
            "burn_rates": {_wlabel(w): self._burn_last[w]
                           for w in self.windows},
            "rules": {
                r.name: {
                    "short_s": r.short_s, "long_s": r.long_s,
                    "threshold": r.threshold,
                    "clear_ratio": r.clear_ratio,
                    **self.alerts[r.name],
                } for r in self.rules},
        }

    def close(self):
        """Unregister the policy's gauges/counters and uninstall it
        (the windowed families stay — other consumers may share
        them)."""
        self.registry.remove(*self._registered)
        uninstall(self)
