"""Health-report schema: declarative serve SLOs and the one-call
:func:`health_report` summary over the whole ``observe`` layer.

Two exports:

* :class:`SLO` — declarative serving targets (``ttft_p99_s``,
  ``tpot_p50_s``, ``queue_depth_max``).  Hand one to
  ``model.serve(slo=...)`` (or ``EngineStats`` directly) and every
  retire is checked against it: a request beyond a target increments
  ``serve.slo_violations{engine=,kind=}`` and emits a trace instant;
  a scheduling pass beyond ``queue_depth_max`` emits a
  ``serve/queue_pressure`` event and a ``kind=queue`` violation.
  Checking per retire (not per scrape) means the counters are exact —
  no violation hides between two polls.
* :func:`health_report` — one JSON-able dict answering "is this
  process healthy and how close to hardware peak does it run":
  host/process info, train steps + MFU accounting
  (``monitor.MfuMeter``), per-process step-time summaries with the
  named straggler, serve goodput + SLO violation counts, watchdog
  hang/anomaly state, flight-recorder status, and the full registry
  snapshot.  ``bench.py`` / ``bench_serve.py`` embed it under their
  reports' ``health`` key and write it standalone via ``--health-out``.

Schema stability: like ``EngineStats.snapshot()``, the report is
extended by ADDING keys, never renaming — ``tests/test_monitor.py``
asserts the section set and CI parses the bench-emitted file.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from . import federate as _federate
from . import monitor as _monitor
from . import requests as _requests
from . import slo as _slo
from . import stepprof as _stepprof
from . import trace as _trace
from .registry import registry as _registry

__all__ = ["SLO", "health_report"]


@dataclass(frozen=True)
class SLO:
    """Serving service-level objectives; ``None`` disables a check.

    ``ttft_p99_s``/``tpot_p50_s`` are named for the dashboard line
    they guard, but they are enforced per REQUEST at retire time (a
    per-request bound is strictly stronger than the percentile it
    protects, and it is exact under any traffic shape).
    """

    ttft_p99_s: float | None = None
    tpot_p50_s: float | None = None
    queue_depth_max: int | None = None

    def asdict(self) -> dict:
        return asdict(self)


def _slo_violations(snap_counters: dict) -> dict:
    """Aggregate ``serve.slo_violations{engine=..,kind=..}`` counters
    across engines into ``{kind: total}``."""
    out = {"ttft": 0, "tpot": 0, "queue": 0}
    for key, v in snap_counters.items():
        if not key.startswith("serve.slo_violations"):
            continue
        for kind in out:
            if f"kind={kind}" in key:
                out[kind] += v
    return out


def _by_label(snap_counters: dict, name: str, label: str) -> dict:
    """Aggregate ``name{...,label=v,...}`` counters into ``{v: total}``
    (pure string work over the registry snapshot — no import of the
    resilience layer, which sits above observe)."""
    out = {}
    prefix = name + "{"
    for key, v in snap_counters.items():
        if not (key == name or key.startswith(prefix)):
            continue
        val = "_"
        if "{" in key:
            for part in key[key.index("{") + 1:-1].split(","):
                k, _, lv = part.partition("=")
                if k == label:
                    val = lv
        out[val] = out.get(val, 0) + v
    return out


def _sum_metric(snap: dict, name: str):
    """Sum a metric across its label sets (``name`` + ``name{...}``)."""
    prefix = name + "{"
    return sum(v for k, v in snap.items()
               if k == name or k.startswith(prefix))


def _prefix_section(snap: dict) -> dict:
    """The ``serve.prefix`` health section: radix prefix-cache
    counters summed across engines (zeros when no engine ever ran a
    cache — always present so dashboards can alert unconditionally).
    ``hit_rate_tokens`` is hit_tokens / lookup_tokens, the fraction
    of admitted prompt tokens served from cached blocks."""
    counters, gauges = snap["counters"], snap["gauges"]
    hit = _sum_metric(counters, "serve.prefix.hit_tokens")
    lookup = _sum_metric(counters, "serve.prefix.lookup_tokens")
    return {
        "hits": _sum_metric(counters, "serve.prefix.hits"),
        "misses": _sum_metric(counters, "serve.prefix.misses"),
        "evictions": _sum_metric(counters, "serve.prefix.evictions"),
        "hit_tokens": hit,
        "lookup_tokens": lookup,
        "hit_rate_tokens": (hit / lookup) if lookup else 0.0,
        "cached_blocks": _sum_metric(gauges,
                                     "serve.prefix.cached_blocks"),
    }


def _paged_section(snap: dict) -> dict:
    """The ``serve.paged`` health section: block-pool accounting and
    preemption/swap counters summed across engines (zeros when no
    paged engine ever ran — always present so dashboards can alert
    unconditionally).  ``blocks_used``/``blocks_free`` are gauges (the
    CURRENT pool state, last-written engine set included); the
    counters are lifetime totals."""
    counters, gauges = snap["counters"], snap["gauges"]
    return {
        "blocks_free": _sum_metric(gauges, "serve.paged.blocks_free"),
        "blocks_used": _sum_metric(gauges, "serve.paged.blocks_used"),
        "preemptions": _sum_metric(counters,
                                   "serve.paged.preemptions"),
        "swap_out": _sum_metric(counters, "serve.paged.swap_out"),
        "swap_in": _sum_metric(counters, "serve.paged.swap_in"),
    }


def _spec_section(snap: dict) -> dict:
    """The ``serve.spec`` health section: speculative-decoding
    acceptance counters summed across engines (zeros when no engine
    ever ran a draft — always present so dashboards can alert
    unconditionally).  ``acceptance_rate`` is accepted / drafted, the
    realized fraction of draft proposals the target verify kept — the
    number that decides whether speculation is still paying on live
    traffic."""
    counters = snap["counters"]
    acc = _sum_metric(counters, "serve.spec.accepted")
    drafted = _sum_metric(counters, "serve.spec.drafted")
    return {
        "accepted": acc,
        "drafted": drafted,
        "acceptance_rate": (acc / drafted) if drafted else 0.0,
    }


def _tp_section(snap: dict) -> dict:
    """The ``serve.tp`` health section: tensor-parallel serving
    (serve/tp.py) — shard width, per-shard KV bytes, and sharded
    dispatch counts (zeros when no TP engine ever ran — always
    present so dashboards can alert unconditionally).  ``shards`` is
    the WIDEST live engine's mesh (gauges max, not sum: two tp=2
    replicas are not a tp=4 engine); bytes/dispatches sum across
    engines."""
    counters, gauges = snap["counters"], snap["gauges"]
    prefix = "serve.tp.shards{"
    widths = [v for k, v in gauges.items()
              if k == "serve.tp.shards" or k.startswith(prefix)]
    return {
        "shards": max(widths) if widths else 0,
        "kv_bytes_per_shard": _sum_metric(
            gauges, "serve.tp.kv_bytes_per_shard"),
        "collectives_per_step": _sum_metric(
            gauges, "serve.tp.collectives_per_step"),
        "sharded_dispatches": _sum_metric(
            counters, "serve.tp.sharded_dispatches"),
    }


def _ep_section(snap: dict) -> dict:
    """The ``serve.ep`` health section: expert-parallel MoE serving
    (serve/ep.py) — expert shard width, per-expert routed-token load,
    dropped assignments, and a max/mean load-imbalance ratio (an
    imbalanced router is the MoE why_slow: collapsed routing shows up
    here before it shows up as expert-shard latency).  Zeros when no
    EP engine ever ran — always present so dashboards can alert
    unconditionally.  ``shards`` is the widest live engine's expert
    mesh (max, like the tp section); token/drop counters sum across
    engines, and expert_tokens sums per expert INDEX across engines
    (same-geometry replicas add up; the imbalance ratio is computed
    over the summed loads)."""
    counters, gauges = snap["counters"], snap["gauges"]
    widths = [v for k, v in gauges.items()
              if k == "serve.ep.shards"
              or k.startswith("serve.ep.shards{")]
    per_expert: dict = {}
    for k, v in counters.items():
        if k == "serve.ep.expert_tokens" \
                or k.startswith("serve.ep.expert_tokens{"):
            e = "0"
            if "expert=" in k:
                e = k.split("expert=")[1].split("}")[0].split(",")[0]
            per_expert[e] = per_expert.get(e, 0) + v
    loads = [per_expert[k] for k in sorted(per_expert, key=int)] \
        if per_expert else []
    total = sum(loads)
    imb = (max(loads) / (total / len(loads))
           if total and loads else None)
    return {
        "shards": max(widths) if widths else 0,
        "kv_bytes_per_shard": _sum_metric(
            gauges, "serve.ep.kv_bytes_per_shard"),
        "sharded_dispatches": _sum_metric(
            counters, "serve.ep.sharded_dispatches"),
        "expert_tokens": loads,
        "dropped_tokens": _sum_metric(
            counters, "serve.ep.dropped_tokens"),
        "load_imbalance": imb,
    }


def _pp_section(snap: dict) -> dict:
    """The ``serve.pp`` health section: pipeline-parallel serving
    (serve/pp.py) — stage depth, microbatch width, per-stage KV
    bytes, and stage-boundary hop counts (zeros when no PP engine
    ever ran — always present so dashboards can alert
    unconditionally).  ``stages`` is the deepest live engine's
    pipeline (max); bytes/dispatches/hops sum across engines."""
    counters, gauges = snap["counters"], snap["gauges"]
    depths = [v for k, v in gauges.items()
              if k == "serve.pp.stages"
              or k.startswith("serve.pp.stages{")]
    mbs = [v for k, v in gauges.items()
           if k == "serve.pp.microbatches"
           or k.startswith("serve.pp.microbatches{")]
    return {
        "stages": max(depths) if depths else 0,
        "microbatches": max(mbs) if mbs else 0,
        "kv_bytes_per_stage": _sum_metric(
            gauges, "serve.pp.kv_bytes_per_stage"),
        "sharded_dispatches": _sum_metric(
            counters, "serve.pp.sharded_dispatches"),
        "boundary_hops": _sum_metric(
            counters, "serve.pp.boundary_hops"),
    }


def _fleet_section(snap: dict) -> dict:
    """The ``serve.fleet`` health section: replicated-serve routing and
    failover counters summed across fleets (zeros when no fleet ever
    ran — always present so dashboards can alert unconditionally).
    ``routed`` is per replica index, summed across fleets."""
    counters, gauges = snap["counters"], snap["gauges"]
    return {
        "replicas_healthy": _sum_metric(
            gauges, "serve.fleet.replicas_healthy"),
        # add-only (autoscale round): healthy minus draining/retired —
        # the set the router admits NEW work to
        "replicas_routable": _sum_metric(
            gauges, "serve.fleet.replicas_routable"),
        "failovers": _sum_metric(counters, "serve.fleet.failovers"),
        "requeues": _sum_metric(counters, "serve.fleet.requeues"),
        "hedges": _sum_metric(counters, "serve.fleet.hedges"),
        "routed": _by_label(counters, "serve.fleet.routed", "replica"),
        # disaggregated serving (the disagg round): completed KV
        # ships, their host bytes, fleet-index warm hits, and
        # cold-but-correct fallbacks
        "ships": _sum_metric(counters, "serve.fleet.ships"),
        "ship_bytes": _sum_metric(counters, "serve.fleet.ship_bytes"),
        "shared_prefix_hits": _sum_metric(
            counters, "serve.fleet.shared_prefix_hits"),
        "ship_fallbacks": _sum_metric(
            counters, "serve.fleet.ship_fallbacks"),
    }


def _resilience_section(snap_counters: dict) -> dict:
    """The ``resilience`` health section: retry/fallback/restart
    counts published by singa_tpu.resilience (zeros when the layer
    never armed — the section is always present so dashboards can
    alert on it unconditionally)."""
    return {
        "retries": _by_label(snap_counters, "resilience.retries",
                             "site"),
        "gave_up": _by_label(snap_counters, "resilience.gave_up",
                             "site"),
        "faults_injected": _by_label(
            snap_counters, "resilience.faults_injected", "site"),
        "checkpoint_saves": snap_counters.get(
            "resilience.checkpoint_saves", 0),
        "checkpoint_fallbacks": snap_counters.get(
            "resilience.checkpoint_fallbacks", 0),
        "checkpoint_async_failures": snap_counters.get(
            "checkpoint.async_failures", 0),
        "engine_failures": snap_counters.get(
            "resilience.engine_failures", 0),
        "engine_restarts": snap_counters.get(
            "resilience.engine_restarts", 0),
        # fleet restart accounting: service-level recovery actions on
        # top of the per-engine restarts above
        "fleet_failovers": _sum_metric(snap_counters,
                                       "serve.fleet.failovers"),
        "fleet_requeues": _sum_metric(snap_counters,
                                      "serve.fleet.requeues"),
        "shed_requests": _by_label(snap_counters,
                                   "serve.shed_requests", "reason"),
    }


def _windowed_section(reg) -> dict:
    """The top-level ``windowed`` section: every windowed family's
    per-window aggregates (observe.timeseries).  Always present;
    ``{"enabled": False}`` until the first
    ``registry.windowed(name, ...)`` registration — the same
    unconditional-assert shape as ``why_slow``."""
    fams = reg.windowed_families()
    if not fams:
        return {"enabled": False}
    return {"enabled": True,
            "families": {name: fams[name].section()
                         for name in sorted(fams)}}


def _autoscale_section(snap: dict) -> dict:
    """The ``serve.autoscale`` health section, derived from the
    ``serve.autoscale.*`` registry family (pure string work, like
    every serve section — observe never imports the serve layer).
    ``{"enabled": False}`` until an Autoscaler registers its gauges."""
    counters, gauges = snap["counters"], snap["gauges"]
    enabled = any(k == "serve.autoscale.replicas"
                  or k.startswith("serve.autoscale.replicas{")
                  for k in gauges)
    if not enabled:
        return {"enabled": False}
    return {
        "enabled": True,
        "replicas": _sum_metric(gauges, "serve.autoscale.replicas"),
        "min_replicas": _sum_metric(gauges,
                                    "serve.autoscale.min_replicas"),
        "max_replicas": _sum_metric(gauges,
                                    "serve.autoscale.max_replicas"),
        "draining": _sum_metric(gauges, "serve.autoscale.draining"),
        "scale_ups": _sum_metric(counters,
                                 "serve.autoscale.scale_ups"),
        "scale_downs": _sum_metric(counters,
                                   "serve.autoscale.scale_downs"),
        "decisions_failed": _sum_metric(
            counters, "serve.autoscale.decisions_failed"),
    }


def _step_time_sections(snap_hists: dict) -> dict:
    """Per-source step-time summaries keyed
    ``{source: {process: summary}}``, plus the named straggler (the
    process with the largest mean) per source — the multi-host "who is
    slow" answer."""
    out = {}
    for key, summ in snap_hists.items():
        if ".step_time{" not in key:
            continue
        source = key.split(".step_time{", 1)[0]
        proc = "0"
        for part in key[key.index("{") + 1:-1].split(","):
            k, _, v = part.partition("=")
            if k == "process":
                proc = v
        out.setdefault(source, {"per_process": {}})[
            "per_process"][proc] = summ
    for source, sec in out.items():
        procs = {p: s for p, s in sec["per_process"].items()
                 if s.get("count")}
        if procs:
            worst = max(procs, key=lambda p: procs[p]["mean"])
            sec["straggler"] = {"process": worst,
                                "mean_s": procs[worst]["mean"]}
        else:
            sec["straggler"] = None
    return out


def _why_slow_with_anatomy() -> dict:
    """The request ledger's why_slow section with the step profiler's
    host-vs-device verdict riding along.  The ledger decomposes WHICH
    requests are slow and in which lifecycle phase; the anatomy rider
    says whether the ENGINE's steps are host-bound or device-bound
    while they were — the two answers compose (a decode-phase p99
    regression plus ``culprit: "host"`` points at the step loop, not
    the model).  The rider only appears when ``stepprof`` is live AND
    has sealed at least one step, so the section's shape is unchanged
    for existing consumers when the profiler is off."""
    section = _requests.why_slow_section()
    if _stepprof._active:
        anatomy = _stepprof.why_slow_summary()
        if anatomy is not None:
            section = dict(section)
            section["step_anatomy"] = anatomy
    return section


def health_report(reg=None, engine_snapshots=(),
                  include_registry=True) -> dict:
    """Build the unified health dict.  ``engine_snapshots``: optional
    ``EngineStats.snapshot()`` dicts to embed under ``serve.engines``
    (goodput/uptime per engine); the registry-derived sections
    (violation counters, step-time summaries) need no arguments.
    ``include_registry=False`` omits the full registry snapshot — for
    callers (the benches) that already embed the snapshot elsewhere in
    the same document and should not duplicate it."""
    reg = reg if reg is not None else _registry()
    snap = reg.snapshot()
    wd = _monitor.watchdog()
    mfu = _monitor.mfu_meter()
    rec = _monitor.flight_recorder()
    engine_snapshots = list(engine_snapshots)

    train_steps = snap["counters"].get("train.steps", 0)
    # read(), not sample(): the report must not reset the meter's
    # rate window under the watchdog poll thread's feet
    mfu_sample = mfu.read() if mfu is not None else None
    report = {
        "schema": "singa_tpu.health/1",
        "host": _monitor._process_info(),
        "train": {
            "steps": train_steps,
            "mfu": mfu_sample["mfu"] if mfu_sample else float("nan"),
            "model_flops_per_s": (mfu_sample["model_flops_per_s"]
                                  if mfu_sample else float("nan")),
            "step_flops": (mfu_sample["step_flops"] if mfu_sample
                           else _monitor.step_flops()),
            "peak_flops_per_s": (mfu_sample["peak_flops_per_s"]
                                 if mfu_sample
                                 else _monitor.peak_flops()),
            "mfu_denominator": "bf16_peak",
        },
        "step_time": _step_time_sections(snap["histograms"]),
        "serve": {
            "engines": engine_snapshots,
            # summed across engines (they serve concurrently, so the
            # process-level rate is the sum) — same scope as the
            # cross-engine slo_violations totals next to it
            "goodput_tokens_per_s": (
                sum(s["throughput"]["goodput_tokens_per_s"]
                    for s in engine_snapshots)
                if engine_snapshots else None),
            "slo_violations": _slo_violations(snap["counters"]),
            "prefix": _prefix_section(snap),
            "paged": _paged_section(snap),
            "spec": _spec_section(snap),
            "tp": _tp_section(snap),
            "ep": _ep_section(snap),
            "pp": _pp_section(snap),
            "fleet": _fleet_section(snap),
            # tail-latency attribution from the request ledger
            # (observe.requests): always present; {"enabled": False}
            # until requests.enable() is called.  When live it
            # decomposes the TTFT/TPOT p99 population and the top-K
            # slowest requests into queue/prefill/decode/stall/hop
            # phase components — the "WHY did p99 regress" answer
            "why_slow": _why_slow_with_anatomy(),
            # per-step host/device decomposition (observe.stepprof):
            # always present; {"enabled": False} until
            # stepprof.enable().  When live it carries per-engine
            # segment fractions (summing to 1 — exact arithmetic over
            # one denominator, the ledger's seal-time idiom) and the
            # device-bubble fraction ROADMAP item 5 is measured by
            "step_anatomy": _stepprof.section(),
            # cross-host federation (observe.federate): always
            # present; {"enabled": False} until a federated DistFleet
            # installs its FleetTelemetry.  When live it carries
            # per-host clock/staleness status and the FLEET-wide
            # why_slow (worker hop detail merged in controller time,
            # straggler host named)
            "dist": _federate.dist_section(),
            # multi-window burn-rate alerting (observe.slo): always
            # present; {"enabled": False} until an SLOPolicy installs
            "slo_alerts": _slo.alerts_section(),
            # signal-driven fleet autoscaling (serve/autoscale.py):
            # always present; {"enabled": False} until an Autoscaler
            # registers — derived from the serve.autoscale.* family
            "autoscale": _autoscale_section(snap),
        },
        # windowed telemetry (observe.timeseries): rate/quantile over
        # the last N seconds next to the all-time registry truth —
        # always present, {"enabled": False} until the first
        # registry.windowed() registration
        "windowed": _windowed_section(reg),
        "resilience": _resilience_section(snap["counters"]),
        "watchdog": (
            {"active": True, **wd.summary()} if wd is not None
            else {"active": False, "hangs": 0, "sources": {}}),
        "flight_recorder": {
            "active": rec.active,
            "events": len(rec),
            "capacity": rec.capacity,
            "trace_dropped": _trace.dropped(),
        },
    }
    if include_registry:
        report["registry"] = snap
    return report
