"""Process-wide metrics registry — the state half of ``singa_tpu.observe``.

Three metric kinds, the Prometheus trinity:

* :class:`Counter` — monotone count (cache misses, tokens emitted,
  collectives issued).
* :class:`Gauge` — last-written level (queue depth, slot occupancy).
* :class:`Histogram` — per-event value distribution; adopts the
  existing :class:`~singa_tpu.utils.metrics.LatencySeries` wholesale,
  so its ``summary()`` is the same count/mean/p50/p99/max schema the
  serving stats already report (nearest-rank percentiles, see
  ``utils.metrics.percentile``).

A metric is identified by ``(name, frozen label set)`` — asking the
registry for the same identity returns the SAME object (get-or-create),
which is what lets independent subsystems (``serve.EngineStats``, the
graph runner, the communicator) accumulate into one process-wide
surface without coordination.  Re-registering an identity as a
different kind raises: silent type morphing is how dashboards break.

The default process registry is reachable via :func:`registry`;
isolated registries (tests, per-bench snapshots) are just
``MetricsRegistry()`` instances.  Export: ``snapshot()`` here (stable
JSON-able dict), Prometheus text exposition in ``export.py``.
"""

from __future__ import annotations

import bisect as _bisect
import threading
import time as _time

from ..utils.metrics import LatencySeries

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "DEFAULT_BUCKETS"]

#: default cumulative-histogram bucket ladder (seconds): latency-
#: shaped, 1ms..2min.  Buckets exist for the PROMETHEUS side — a
#: summary's precomputed quantiles cannot be aggregated across a fleet
#: of replicas, while ``sum(rate(x_bucket[5m])) by (le)`` +
#: ``histogram_quantile()`` can.  Override per metric via
#: ``registry.histogram(name, buckets=...)``.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


class _Metric:
    __slots__ = ("name", "labels", "help", "_lock", "_rings")

    KIND = "metric"

    def __init__(self, name, labels, help=""):
        self.name = name
        self.labels = labels  # tuple of (key, value) pairs, sorted
        self.help = help
        # per-metric lock: `value += n` is a read-modify-write across
        # bytecodes, and the observe layer promises concurrent use
        # (async-checkpoint writer thread + main loop)
        self._lock = threading.Lock()
        # windowed-telemetry rings (observe.timeseries), attached by
        # ``MetricsRegistry.windowed``: every value write appends the
        # new value.  Empty tuple when no window is registered — the
        # hot-path cost of the feature being off is one truthiness
        # check.
        self._rings = ()

    @property
    def key(self):
        return (self.name, self.labels)


class Counter(_Metric):
    """Monotonically increasing count."""

    __slots__ = ("value",)

    KIND = "counter"

    def __init__(self, name, labels=(), help=""):
        super().__init__(name, labels, help)
        self.value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc({n})); use a Gauge")
        with self._lock:
            self.value += n
            # inside the lock: two concurrent incs must append their
            # cumulative samples in value order, or a ring's newest
            # sample can sit BELOW the true cumulative value and
            # under-report the window's growth
            if self._rings:
                for r in self._rings:
                    r.append(self.value)
        return self


class Gauge(_Metric):
    """Last-written level; ``set``/``inc``/``dec``."""

    __slots__ = ("value",)

    KIND = "gauge"

    def __init__(self, name, labels=(), help=""):
        super().__init__(name, labels, help)
        self.value = 0.0

    def set(self, v):
        self.value = v
        if self._rings:
            for r in self._rings:
                r.append(v)
        return self

    def inc(self, n=1):
        with self._lock:
            self.value += n
            if self._rings:  # in value order — see Counter.inc
                for r in self._rings:
                    r.append(self.value)
        return self

    def dec(self, n=1):
        with self._lock:
            self.value -= n
            if self._rings:  # in value order — see Counter.inc
                for r in self._rings:
                    r.append(self.value)
        return self


class Histogram(_Metric):
    """Value distribution over a :class:`LatencySeries` (count/mean/
    p50/p99/max summary schema).  ``buckets``: cumulative upper bounds
    for the Prometheus ``_bucket{le=...}`` exposition (+Inf is
    implicit); defaults to :data:`DEFAULT_BUCKETS`."""

    __slots__ = ("series", "buckets", "_bins")

    KIND = "histogram"

    def __init__(self, name, labels=(), help="", series=None,
                 buckets=None):
        super().__init__(name, labels, help)
        self.series = series if series is not None else LatencySeries()
        if buckets is None:
            self.buckets = DEFAULT_BUCKETS
        else:
            b = tuple(float(x) for x in buckets)
            if not b or list(b) != sorted(set(b)):
                raise ValueError(
                    f"buckets must be non-empty, strictly increasing, "
                    f"got {buckets}")
            self.buckets = b
        # per-ladder-bin counts, filled AT RECORD TIME through the
        # series' hook seam: adopters record into the series directly
        # (EngineStats), so observe() cannot be the binning point, and
        # the series' retained-value ring is BOUNDED (values age out),
        # so a read-side catch-up could miss evicted values.  A
        # record-time hook is O(log buckets) per event and keeps the
        # cumulative bins exact over all time — the Prometheus
        # histogram contract — regardless of the retained window.
        self._bins = [0] * len(self.buckets)
        for v in self.series.values:  # adopt pre-existing samples
            self._bin(v)
        self.series.add_hook(self._bin)

    def _bin(self, v):
        i = _bisect.bisect_left(self.buckets, v)
        if i < len(self._bins):
            self._bins[i] += 1

    def observe(self, v):
        self.series.record(v)
        return self

    @property
    def count(self):
        return self.series.count

    def bucket_counts(self) -> list:
        """Cumulative ``(le, count)`` pairs, ending with ``(inf,
        count)``.  Bins are maintained at record time (O(log buckets)
        per event), so a scrape's cost does not grow with process
        uptime and the bins stay cumulative over all time even though
        the retained value window is bounded.  The +Inf bucket uses
        the series' RUNNING count (same source as ``_count``), so
        ``x_bucket{le="+Inf"} == x_count`` always holds."""
        out, c = [], 0
        for le, n in zip(self.buckets, self._bins):
            c += n
            out.append((le, c))
        out.append((float("inf"), self.series.count))
        return out

    def summary(self) -> dict:
        return self.series.summary()


def _label_key(labels: dict):
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Name+labels -> metric map with get-or-create semantics."""

    def __init__(self):
        self._metrics = {}
        self._kinds = {}  # name -> metric class (one kind per name)
        self._windowed = {}  # name -> timeseries.WindowedFamily
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, labels, help, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                # one kind per NAME, not just per (name, labels): a
                # Prometheus family declares a single TYPE, so a
                # counter x{op=a} next to a gauge x{op=b} would render
                # an exposition conformant scrapers reject
                prior = self._kinds.get(name)
                if prior is not None and prior is not cls:
                    raise TypeError(
                        f"metric name {name!r} already registered as "
                        f"{prior.KIND}, requested {cls.KIND} (one kind "
                        f"per name — Prometheus families share a TYPE)")
                m = cls(name, key[1], help=help, **kw)
                self._metrics[key] = m
                self._kinds[name] = cls
                wf = self._windowed.get(name)
                if wf is not None:
                    # the family pre-dates this label set (a fleet
                    # scale-up registering a new engine's counters):
                    # windowing follows the name, not the moment
                    wf._attach(m)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{dict(key[1])} already registered "
                    f"as {m.KIND}, requested {cls.KIND}")
            return m

    def counter(self, name, help="", **labels) -> Counter:
        return self._get_or_create(Counter, name, labels, help)

    def gauge(self, name, help="", **labels) -> Gauge:
        return self._get_or_create(Gauge, name, labels, help)

    def histogram(self, name, help="", series=None, buckets=None,
                  **labels) -> Histogram:
        """``series``: adopt an existing LatencySeries as the backing
        store (EngineStats hands its TTFT/TPOT series over this way —
        one copy of the data, two views).  ``buckets``: per-metric
        Prometheus bucket-ladder override (first registration wins —
        get-or-create semantics)."""
        return self._get_or_create(Histogram, name, labels, help,
                                   series=series, buckets=buckets)

    def windowed(self, name, windows=None, capacity=None,
                 clock=None) -> "WindowedFamily":
        """Attach windowed telemetry (observe.timeseries) to every
        metric named ``name`` — current AND future label sets — and
        return the :class:`~singa_tpu.observe.timeseries
        .WindowedFamily` (get-or-create: asking again for the same
        name returns the SAME family; windows/capacity/clock are
        first-registration-wins, like histogram buckets).

        >>> wf = registry().windowed("serve.completed", windows=(60,))
        >>> wf.rate(60)      # completions/s over the last minute

        The family's values ride ``export.prometheus_text`` as sibling
        ``<name>_rate_60s``-style gauges and
        ``health_report()["windowed"]``; the all-time family is
        untouched.  Memory: one bounded ring per label set, O(ring)
        forever."""
        from .timeseries import (DEFAULT_RING_CAPACITY,
                                 DEFAULT_WINDOWS, WindowedFamily)

        with self._lock:
            wf = self._windowed.get(name)
            if wf is None:
                kind = self._kinds.get(name)
                wf = WindowedFamily(
                    name,
                    kind.KIND if kind is not None else None,
                    windows=(windows if windows is not None
                             else DEFAULT_WINDOWS),
                    capacity=(capacity if capacity is not None
                              else DEFAULT_RING_CAPACITY),
                    clock=clock if clock is not None else _time.monotonic)
                self._windowed[name] = wf
                for (n, _), m in self._metrics.items():
                    if n == name:
                        wf._attach(m)
            return wf

    def windowed_families(self) -> dict:
        """``{name: WindowedFamily}`` of every windowed registration
        (the health report's ``windowed`` section source)."""
        with self._lock:
            return dict(self._windowed)

    def unwindow(self, name):
        """Drop a windowed family (tests, policy teardown).  The
        attached counter/gauge rings stop being read and are dropped;
        histogram series hooks are detached."""
        with self._lock:
            wf = self._windowed.pop(name, None)
            if wf is None:
                return
            for (n, _), m in self._metrics.items():
                if n == name:
                    wf._detach_metric(m)

    def metrics(self) -> list:
        """All registered metrics, in stable (name, labels) order."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def remove(self, *metrics):
        """Unregister metric objects (e.g. a retired engine's
        ``serve.*`` set — see ``EngineStats.unregister``) so a
        process-lifetime registry doesn't pin dead subsystems'
        histograms forever.  Unknown metrics are ignored.  A name
        whose last metric is removed frees its kind reservation too.
        Windowed rings attached to the removed metrics are detached
        with them — a retired engine's windowed series disappears
        instead of freezing at its last value (the scale-down
        leaked-gauge contract)."""
        with self._lock:
            for m in metrics:
                self._metrics.pop(m.key, None)
                wf = self._windowed.get(m.name)
                if wf is not None:
                    wf._detach_metric(m)
            names = {name for name, _ in self._metrics}
            for name in [n for n in self._kinds if n not in names]:
                del self._kinds[name]

    def clear(self):
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._windowed.clear()

    def dump(self) -> dict:
        """Wire-serializable full dump (the federation telemetry
        schema, ``observe.federate``): one dict per metric with
        name/kind/labels/help, plus value (counter/gauge) or the full
        cumulative bucket ladder + running sum/count + exact
        nearest-rank p50/p99 (histogram).  Unlike :meth:`snapshot`
        this ships the BUCKETS, so a controller can re-expose a
        worker's histograms as real TYPE-histogram families that
        ``histogram_quantile`` aggregates across hosts."""
        out = []
        for m in self.metrics():
            d = {"name": m.name, "kind": m.KIND,
                 "labels": [list(kv) for kv in m.labels],
                 "help": m.help}
            if isinstance(m, Histogram):
                d["buckets"] = [[le, c] for le, c in
                                m.bucket_counts()]
                d["sum"] = m.series.total_sum
                d["count"] = m.series.count
                d["p50"] = m.series.percentile(50)
                d["p99"] = m.series.percentile(99)
            else:
                d["value"] = m.value
            out.append(d)
        return {"schema": "singa_tpu.telemetry/1", "metrics": out}

    def snapshot(self) -> dict:
        """JSON-able view: ``{"counters": {...}, "gauges": {...},
        "histograms": {...}}`` keyed ``name{k=v,...}`` (labels sorted,
        braces omitted when unlabeled)."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            k = m.name
            if m.labels:
                k += "{" + ",".join(f"{lk}={lv}"
                                    for lk, lv in m.labels) + "}"
            if isinstance(m, Counter):
                out["counters"][k] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][k] = m.summary()
            else:
                out["gauges"][k] = m.value
        return out


_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _default
