"""Active monitoring over the passive ``observe`` layer: flight
recorder, crash bundles, MFU/goodput accounting, and a hang/anomaly
watchdog.

PR 2's ``trace``/``registry``/``export`` record; nothing there
*interprets* the stream, survives a crash, or says whether the process
is healthy.  This module adds the four pieces every production
training/serving stack grows:

* **flight recorder** — a bounded ring of the most recent span/instant
  records, fed by the same ``trace`` instrumentation sites but
  INDEPENDENT of ``trace.enable()`` (the ring attaches via
  ``trace._attach_ring``; the main buffer stays empty unless tracing
  is on).  Cheap enough to leave on for a whole run, so a crash always
  has the last N events on hand.
* **crash bundles** — :func:`dump_report` writes a single JSON file
  with the recent events, a full registry snapshot, the compiled-step
  XLA cost tables, and process/host info; :func:`install_crash_handler`
  wires it to ``sys.excepthook`` and SIGTERM/SIGINT so an OOM-killed or
  preempted run leaves forensics behind.
* **MFU / goodput** — :class:`MfuMeter` turns the XLA per-step flops
  the graph runner already captures (``model._GraphRunner.cost_tables``)
  times the observed ``train.steps`` rate into
  ``train.model_flops_per_s``, and divides by a per-backend peak-FLOPs
  table into ``train.mfu``.  Unknown backends (CPU included) publish
  an honest ``nan``, never 0: a fake denominator is worse than none.
* **watchdog** — a background thread fed by :func:`heartbeat` calls
  from ``_GraphRunner.run`` and the serve decode loop.  A missed
  heartbeat emits a ``monitor/hang`` event carrying every thread's
  stack (``sys._current_frames``) and dumps a crash bundle; an EWMA
  z-score over step times increments ``<source>.step_time_anomalies``
  and attaches a trace instant; each host feeds a
  ``{process=<index>}``-labeled step-time histogram so a multi-process
  health report can name the straggler.  The clock is injectable and
  ``check()`` is callable without the thread, so every firing rule is
  deterministic in tests.

Everything is off until :func:`start`; a stopped monitor costs the
instrumented sites one ``is None`` check per step.  The one-call
summary over all of it is :func:`observe.health_report()
<singa_tpu.observe.health.health_report>` (observe/health.py).
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

from . import stepprof as _stepprof
from . import trace as _trace
from .registry import registry as _registry

__all__ = ["FlightRecorder", "flight_recorder", "dump_report",
           "install_crash_handler", "uninstall_crash_handler",
           "peak_flops", "step_flops", "MfuMeter", "Watchdog",
           "heartbeat", "start", "stop", "active", "watchdog",
           "crash_dir"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Bounded ring of the most recent trace records, independent of
    ``trace.enable()``.  While started, every ``span()``/``event()``
    emission lands here too (deque append, GIL-atomic); the ring
    forgets beyond ``capacity``, so a forgotten recorder cannot OOM —
    it holds exactly the tail a post-mortem wants."""

    def __init__(self, capacity=2048):
        self.capacity = int(capacity)
        self._ring = deque(maxlen=self.capacity)
        self._started = False

    @property
    def active(self) -> bool:
        return self._started

    def start(self, capacity=None):
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = int(capacity)
            self._ring = deque(self._ring, maxlen=self.capacity)
        _trace._attach_ring(self._ring)
        self._started = True
        return self

    def stop(self):
        self._started = False
        _trace._attach_ring(None)

    def clear(self):
        self._ring.clear()

    def events(self) -> list:
        """Snapshot copy of the ring, oldest first."""
        return list(self._ring)

    def __len__(self):
        return len(self._ring)


_recorder = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (started by :func:`start` or
    explicitly via ``flight_recorder().start()``)."""
    return _recorder


# ---------------------------------------------------------------------------
# crash bundles
# ---------------------------------------------------------------------------

def crash_dir() -> str:
    """Where crash bundles land: $SINGA_TPU_CRASH_DIR, else the system
    temp dir."""
    import tempfile

    return os.environ.get("SINGA_TPU_CRASH_DIR", tempfile.gettempdir())


def _process_info() -> dict:
    info = {
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "time_unix": time.time(),
    }
    try:
        info["hostname"] = __import__("socket").gethostname()
    except Exception:
        pass
    try:
        from ..parallel.communicator import process_info

        info.update(process_info())
    except Exception:
        pass
    return info


#: extra cost-table providers (serve-side AOT compiles — the paged
#: pool steps register one): zero-arg callables returning
#: [{"key": str, "cost": {scalars}}] entries for crash bundles
_extra_cost_sources = []


def register_cost_source(provider):
    """Register a zero-arg callable contributing XLA cost-table
    entries to :func:`dump_report` bundles alongside the graph
    runners' tables.  Serve-side executables (``serve/paged.py``'s
    AOT-compiled pool steps) use this so their compiles are just as
    visible post-mortem as a train step's."""
    if provider not in _extra_cost_sources:
        _extra_cost_sources.append(provider)


def _cost_tables() -> list:
    """Every graph runner's XLA cost tables (scalar entries only —
    the full tables carry per-op rows that can run to megabytes),
    plus any registered extra sources' entries."""
    out = []
    try:
        from ..model import _compiled_cost_tables, _cost_args
    except Exception:
        pass
    else:
        for key, cost in _compiled_cost_tables():
            out.append({"key": key, "cost": _cost_args(cost)})
    for provider in _extra_cost_sources:
        try:
            out.extend(provider())
        except Exception:
            pass  # a broken telemetry source must not break bundles
    return out


def _thread_stacks() -> dict:
    """All-thread stacks keyed by thread name — the hang forensic."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        name = names.get(tid, str(tid))
        out[name] = "".join(traceback.format_stack(frame))
    return out


def dump_report(path=None, reason=None, extra=None) -> str:
    """Write a crash/health bundle and return its path: the flight
    recorder's recent events, the full registry snapshot, the compiled
    steps' XLA cost tables, config/env, and process/host info — one
    self-contained, ``json.loads``-able post-mortem file."""
    if path is None:
        path = os.path.join(
            crash_dir(),
            f"monitor-crash-{os.getpid()}-{int(time.time() * 1000)}.json")
    wd = _watchdog
    report = {
        "schema": "singa_tpu.crash/1",
        "reason": reason,
        "host": _process_info(),
        "config": {k: v for k, v in os.environ.items()
                   if k.startswith(("SINGA", "JAX", "XLA", "BENCH"))},
        "recent_events": _recorder.events(),
        "trace_dropped": _trace.dropped(),
        "registry": _registry().snapshot(),
        "cost_tables": _cost_tables(),
        "watchdog": wd.summary() if wd is not None else None,
    }
    if extra:
        report.update(extra)
    from .export import json_sanitize

    with open(path, "w") as f:
        # default=str: recent events carry numpy/jax scalars in args;
        # a crash bundle must never be lost at dump time over a dtype.
        # json_sanitize: nan/inf floats become null so the bundle is
        # STRICT JSON, readable by any tooling, not just Python
        json.dump(json_sanitize(report), f, default=str)
    return path


_prev_excepthook = None
_prev_signal = {}
_signal_dumped = set()  # signums whose handler already wrote a bundle


def install_crash_handler(dir=None, signals=(signal.SIGTERM,
                                             signal.SIGINT)):
    """Wire :func:`dump_report` to ``sys.excepthook`` and the given
    signals, and start the flight recorder if it isn't running (a
    crash handler without a ring would dump an empty tail).  The
    previous excepthook/handlers are chained, not replaced; idempotent.
    Signal handlers are skipped off the main thread (CPython rule)."""
    global _prev_excepthook
    if dir is not None:
        os.environ["SINGA_TPU_CRASH_DIR"] = dir
    if not _recorder.active:
        _recorder.start()
    if _prev_excepthook is None:
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            # Ctrl-C with our SIGINT handler installed already wrote a
            # signal:2 bundle before default_int_handler raised this
            # KeyboardInterrupt — one incident, one bundle
            dup = (issubclass(exc_type, KeyboardInterrupt)
                   and signal.SIGINT in _signal_dumped)
            if not dup:
                try:
                    dump_report(
                        reason=f"uncaught:{exc_type.__name__}: {exc}",
                        extra={"traceback": "".join(
                            traceback.format_exception(exc_type, exc,
                                                       tb))})
                except Exception:
                    pass  # the original exception must still surface
            prev(exc_type, exc, tb)

        _prev_excepthook = prev
        sys.excepthook = hook
    for sig in signals:
        if sig in _prev_signal:
            continue
        try:
            old = signal.getsignal(sig)

            def handler(signum, frame, _old=old):
                try:
                    dump_report(reason=f"signal:{signum}")
                    _signal_dumped.add(signum)
                except Exception:
                    pass
                if _old is signal.SIG_IGN:
                    # the signal was a deliberate no-op before us
                    # (shell background jobs ignore SIGINT, shielding
                    # supervisors ignore SIGTERM) — dump forensics but
                    # do NOT turn an ignored signal into a fatal one
                    return
                if callable(_old):
                    _old(signum, frame)
                else:
                    # restore the default disposition and re-raise so
                    # the process dies with the right signal status
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal.signal(sig, handler)
            _prev_signal[sig] = old
        except ValueError:
            pass  # not the main thread


def uninstall_crash_handler():
    """Restore the previous excepthook/signal handlers (tests)."""
    global _prev_excepthook
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    for sig, old in list(_prev_signal.items()):
        try:
            signal.signal(sig, old)
        except ValueError:
            pass
        del _prev_signal[sig]
    _signal_dumped.clear()


# ---------------------------------------------------------------------------
# MFU accounting
# ---------------------------------------------------------------------------

# bf16 peak matmul FLOP/s per chip, by device_kind substring (first
# match wins — list "v5p"/"v5e" before the bare "v5").  The honest
# limits of this table: peaks are the MXU's dense-bf16 datasheet
# numbers, so fp32 workloads (executed as multi-pass bf16) and
# int8/fp8 paths make the ratio conservative/optimistic respectively;
# unknown kinds (CPU, future TPUs) get nan, never a guess.
_PEAK_FLOPS = [
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v6", 918e12),
]


def peak_flops(device_kind=None) -> float:
    """Per-chip bf16 peak for a ``device_kind`` string (default: the
    current backend's first device); nan when unknown — the MFU of an
    unmodeled chip is unknowable, not zero."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            return float("nan")
    kind = str(device_kind).lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return float("nan")


def step_flops() -> float:
    """FLOPs of one compiled training step, from the XLA cost analysis
    the graph runner captured at compile time; the LARGEST table wins
    (eval/probe compiles ride the same cache).  nan when no graph step
    has compiled or the backend reported no cost analysis."""
    best = float("nan")
    for entry in _cost_tables():
        f = entry["cost"].get("flops")
        if f and not (best == best and best >= f):  # best is nan or < f
            best = float(f)
    return best


class MfuMeter:
    """Publishes ``train.model_flops_per_s`` and ``train.mfu`` gauges
    from the ``train.steps`` counter rate × per-step XLA flops ÷ the
    backend peak.  ``sample()`` is rate-over-interval: call it
    periodically (the watchdog thread does) or once at report time.
    Both gauges hold nan until the first samplable interval — and stay
    nan on backends with no cost table or no peak entry."""

    #: intervals shorter than this neither reset the window nor
    #: republish: a report landing right after a watchdog-thread
    #: sample would otherwise see 0 steps over ~0 seconds and publish
    #: a misleading 0 for a process that just trained at high
    #: utilization
    MIN_INTERVAL_S = 0.5

    def __init__(self, reg=None, clock=time.monotonic):
        reg = reg if reg is not None else _registry()
        self._reg = reg
        self._clock = clock
        self._g_flops = reg.gauge(
            "train.model_flops_per_s",
            help="XLA step flops x observed train.steps rate")
        self._g_mfu = reg.gauge(
            "train.mfu",
            help="model_flops_per_s / per-chip bf16 peak (nan when "
                 "peak or cost table unknown)")
        self._g_flops.set(float("nan"))
        self._g_mfu.set(float("nan"))
        self._last = (clock(), self._steps())
        self.last = None  # most recent published sample dict

    def _steps(self) -> int:
        return self._reg.counter("train.steps").value

    def sample(self) -> dict:
        """One accounting interval; returns (and publishes) the rates
        since the previous ``sample()``/construction.  Back-to-back
        calls inside ``MIN_INTERVAL_S`` return the previous sample
        unchanged instead of resetting the window."""
        now, steps = self._clock(), self._steps()
        t0, s0 = self._last
        dt = now - t0
        if dt < self.MIN_INTERVAL_S:
            if self.last is not None:
                return self.last
            # no samplable interval yet either: report nan WITHOUT
            # publishing or resetting — steps-s0==0 over a ~0s window
            # would otherwise publish mfu=0 for a process that may be
            # training flat-out (the misleading zero this class's
            # contract forbids)
            nan = float("nan")
            return {"steps_per_s": nan, "step_flops": step_flops(),
                    "model_flops_per_s": nan,
                    "peak_flops_per_s": peak_flops(), "mfu": nan}
        self._last = (now, steps)
        rate = (steps - s0) / dt if dt > 0 else float("nan")
        f = step_flops()
        # a ZERO-step interval (a process serving, checkpointing, or
        # between phases) publishes nan, not a hard 0.0: a busy
        # process must never read as 0 flops/s, and model_flops_per_s
        # and mfu must go honest-nan TOGETHER — unknown-peak backends
        # used to report flops 0.0 next to mfu null, an inconsistent
        # pair (the committed BENCH_SERVE health.train bug)
        model_fps = (f * rate if steps != s0
                     else float("nan"))  # nan propagates from f/rate
        peak = peak_flops()
        mfu = model_fps / peak  # nan when peak unknown (CPU)
        self._g_flops.set(model_fps)
        self._g_mfu.set(mfu)
        self.last = {"steps_per_s": rate, "step_flops": f,
                     "model_flops_per_s": model_fps,
                     "peak_flops_per_s": peak, "mfu": mfu}
        return self.last

    def read(self) -> dict:
        """Most recent published sample WITHOUT mutating the sampling
        window — what reports should call: ``health_report()`` racing
        the watchdog poll thread must not shrink its interval to ~0
        and overwrite a real rate with 0."""
        return self.last if self.last is not None else self.sample()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

class _SourceState:
    __slots__ = ("last_beat", "beats", "hang_fired", "armed",
                 "ewma_mean", "ewma_var", "n_samples", "hist", "anom")

    def __init__(self, now):
        self.last_beat = now
        self.beats = 0
        self.hang_fired = False
        self.armed = True
        self.ewma_mean = 0.0
        self.ewma_var = 0.0
        self.n_samples = 0
        self.hist = None
        self.anom = None


class Watchdog:
    """Hang + step-time-anomaly detector over :func:`heartbeat`\\ s.

    * **hangs** — an ARMED source that beat at least once and then
      stays silent past ``timeout_s`` fires exactly ONCE (latched
      until the next beat): ``monitor.hangs{source=}`` counter, a
      ``monitor/hang`` instant carrying all-thread stacks, and a
      flight-recorder crash bundle.  Repeated ``check()``\\ s do not
      re-fire — a wedged step is one incident, not one per poll.
      A beat with ``busy=False`` DISARMS the source (idle is not
      hung): the serve engine disarms when it drains, so a healthy
      traffic lull never fires.  Train stays armed between dispatches
      — size ``timeout_s`` above legitimate gaps (eval, checkpoint).
    * **step-time anomalies** — each beat's ``step_time`` is z-scored
      against an EWMA mean/variance (checked BEFORE the sample updates
      the estimate, after ``warmup`` samples); beyond ``z_threshold``
      it increments ``<source>.step_time_anomalies`` and attaches a
      trace instant.  Fresh-compile dispatches are beat-only: a
      compile is minutes against milliseconds and would poison the
      estimator (and the straggler histogram) for the rest of the run.
    * **straggler attribution** — step times feed a
      ``<source>.step_time{process=<jax.process_index()>}`` histogram;
      in multi-process runs every host publishes its own summary, so
      the health report can name the slow one.

    ``clock`` is injectable and ``check()`` needs no thread — tests
    drive every rule deterministically; ``start()`` runs ``check()``
    (plus an MFU sample) every ``poll_interval_s`` on a daemon
    thread."""

    def __init__(self, timeout_s=300.0, poll_interval_s=5.0, clock=None,
                 reg=None, z_threshold=6.0, warmup=8, ewma_alpha=0.2,
                 dump_on_hang=True, mfu=None):
        self.timeout_s = float(timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._clock = clock if clock is not None else time.monotonic
        self._reg = reg if reg is not None else _registry()
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup)
        self.alpha = float(ewma_alpha)
        self.dump_on_hang = dump_on_hang
        self.mfu = mfu
        self._sources = {}
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self.last_dump = None
        try:
            import jax

            self._process = str(jax.process_index())
        except Exception:
            self._process = "0"
        self._hang_total = 0  # across sources (registry counters are
        #                       per source label)

    # -- feeding ---------------------------------------------------------
    def beat(self, source, step_time=None, steps=1, fresh_compile=False,
             busy=True):
        """``busy=False`` marks the source idle-by-choice: liveness is
        refreshed but hang detection is DISARMED until the next busy
        beat — a drained serve engine is not a wedged one."""
        st = self._sources.get(source)
        if st is None:
            with self._lock:
                st = self._sources.setdefault(
                    source, _SourceState(self._clock()))
                if st.hist is None:
                    st.hist = self._reg.histogram(
                        f"{source}.step_time",
                        help="per-dispatch step seconds",
                        process=self._process)
                    st.anom = self._reg.counter(
                        f"{source}.step_time_anomalies",
                        help="EWMA z-score outliers", process=self._process)
        st.last_beat = self._clock()
        st.beats += steps
        st.hang_fired = False
        st.armed = busy
        if step_time is None or fresh_compile:
            return
        dt = step_time / max(steps, 1)
        if st.n_samples >= self.warmup and st.ewma_var > 0:
            z = (dt - st.ewma_mean) / math.sqrt(st.ewma_var)
            if z > self.z_threshold:
                st.anom.inc()
                extra = {}
                if _stepprof._active:
                    # the step profiler names the CULPRIT lane for
                    # this source's anomaly — host-bound (scheduling
                    # bubble) vs device-bound (model got slower) —
                    # from its most recent sealed step, so the alert
                    # carries the answer, not just the symptom
                    verdict = _stepprof.culprit(source)
                    if verdict is not None:
                        extra = verdict
                _trace.event(
                    "monitor/step_time_anomaly", cat="monitor",
                    source=source, step_time=dt, z=round(z, 2),
                    ewma_mean=st.ewma_mean, **extra)
        a = self.alpha
        if st.n_samples == 0:
            st.ewma_mean = dt
        else:
            d = dt - st.ewma_mean
            st.ewma_mean += a * d
            st.ewma_var = (1 - a) * (st.ewma_var + a * d * d)
        st.n_samples += 1
        st.hist.observe(dt)

    # -- checking --------------------------------------------------------
    def check(self) -> list:
        """One watchdog pass; returns the sources that newly hung."""
        now = self._clock()
        fired = []
        for source, st in list(self._sources.items()):
            if (not st.armed or st.hang_fired
                    or now - st.last_beat <= self.timeout_s):
                continue
            st.hang_fired = True
            fired.append(source)
            self._hang_total += 1
            self._reg.counter(
                "monitor.hangs", help="missed-heartbeat incidents",
                source=source).inc()
            stacks = _thread_stacks()
            _trace.event("monitor/hang", cat="monitor", source=source,
                         silent_s=now - st.last_beat,
                         threads=list(stacks))
            if self.dump_on_hang:
                try:
                    self.last_dump = dump_report(
                        reason=f"hang:{source}",
                        extra={"thread_stacks": stacks})
                except Exception:
                    pass
        return fired

    @property
    def hangs(self) -> int:
        return self._hang_total

    def hang_latched(self, source) -> bool:
        """True when ``source``'s heartbeat latched a hang.  The fleet
        probes every replica per step — this is the one-field read
        that keeps that probe off :meth:`summary`'s full dict build
        (clock reads + EWMA/anomaly fields for EVERY source in the
        process)."""
        st = self._sources.get(source)
        return st is not None and st.hang_fired

    def summary(self) -> dict:
        now = self._clock()
        return {
            "timeout_s": self.timeout_s,
            "hangs": self.hangs,
            "last_dump": self.last_dump,
            "sources": {
                s: {"beats": st.beats,
                    "last_heartbeat_age_s": now - st.last_beat,
                    "step_time_ewma_s": st.ewma_mean,
                    "anomalies": st.anom.value if st.anom else 0,
                    "armed": st.armed,
                    "hang_latched": st.hang_fired}
                for s, st in self._sources.items()},
        }

    def forget(self, source):
        """Drop a retired source's state and unregister its step-time
        metrics (the serve engine calls this at ``close()`` — without
        it every per-engine heartbeat source would pin its histogram's
        value list for process lifetime, the same leak
        ``EngineStats.unregister`` exists to prevent)."""
        st = self._sources.pop(source, None)
        if st is not None and st.hist is not None:
            self._reg.remove(st.hist, st.anom)

    # -- thread ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.poll_interval_s):
                try:
                    self.check()
                    if self.mfu is not None:
                        self.mfu.sample()
                except Exception:
                    pass  # the watchdog must never kill the run

        self._thread = threading.Thread(
            target=loop, name="singa-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_interval_s + 1)
            self._thread = None


# ---------------------------------------------------------------------------
# module-level lifecycle (what the benches and instrumented sites use)
# ---------------------------------------------------------------------------

_watchdog = None
_mfu = None


def active() -> bool:
    """True when :func:`start` has run — the instrumented hot paths
    gate their two extra clock calls on this."""
    return _watchdog is not None


def watchdog() -> Watchdog | None:
    return _watchdog


def mfu_meter() -> MfuMeter | None:
    return _mfu


def heartbeat(source, step_time=None, steps=1, fresh_compile=False,
              busy=True):
    """Liveness + step-time feed from the hot loops (graph runner,
    serve decode).  No-op (one ``is None`` check) until ``start()``.
    ``busy=False`` disarms hang detection for the source (idle, not
    hung) until its next busy beat."""
    wd = _watchdog
    if wd is None:
        return
    wd.beat(source, step_time=step_time, steps=steps,
            fresh_compile=fresh_compile, busy=busy)


def forget(source):
    """Drop a retired heartbeat source (see ``Watchdog.forget``)."""
    wd = _watchdog
    if wd is not None:
        wd.forget(source)


def start(watchdog_timeout_s=300.0, poll_interval_s=5.0,
          recorder_capacity=2048, clock=None, reg=None, thread=True,
          crash_handler=False, **watchdog_kw) -> Watchdog:
    """Turn monitoring on: flight recorder attached, MFU meter
    registered, watchdog created (threaded unless ``thread=False`` —
    tests drive ``check()`` by hand with an injected ``clock``).
    Idempotent while running."""
    global _watchdog, _mfu
    if _watchdog is not None:
        return _watchdog
    _recorder.start(capacity=recorder_capacity)
    _mfu = MfuMeter(reg=reg, clock=clock if clock is not None
                    else time.monotonic)
    _watchdog = Watchdog(timeout_s=watchdog_timeout_s,
                         poll_interval_s=poll_interval_s, clock=clock,
                         reg=reg, mfu=_mfu, **watchdog_kw)
    if crash_handler:
        install_crash_handler()
    if thread:
        _watchdog.start()
    return _watchdog


def stop(keep_recorder=False):
    """Stop the watchdog thread and (unless ``keep_recorder``) detach
    the flight recorder."""
    global _watchdog, _mfu
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None
    _mfu = None
    if not keep_recorder:
        _recorder.stop()
