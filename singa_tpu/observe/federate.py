"""Fleet-wide observability federation: see across the process boundary.

PR 16 took serving multi-host (``serve/dist/``) but left every
observability surface per-process: a request that prefills on worker A
and decodes on worker B has two disjoint ledgers, two registries, and
no merged timeline.  This module is the controller-side other half —
the Dapper story for the dist fleet:

* :class:`ClockSync` — NTP-style clock-offset estimation over the
  existing framed ``Conn.call``: N ping round trips per peer, keep the
  minimum-RTT sample (the one least contaminated by queueing), offset
  = ``peer_time - (t0 + t1) / 2``.  The estimate's error is bounded by
  RTT/2 by construction — the peer answered SOMEWHERE inside the round
  trip, and the midpoint is never more than half the trip away from
  any point in it.  Offsets are applied at MERGE time (worker records
  stay in their own clock on the wire) and re-estimated on every
  reconnect — ``_new_supervisor`` runs on spawn, ``revive``, and the
  autoscaler's ``replace_dead``, so a replacement process's fresh
  monotonic base is never mixed with its predecessor's.

* :class:`FleetTelemetry` — the merge point.  Workers ship registry
  dumps, sealed RequestLedger records, and drained trace events as
  framed ``telemetry`` replies (periodic pull from the fleet's
  watchdog slot + on-demand ``pull()``); the controller merges them
  into

  - one Chrome trace: one pid per host, worker timestamps shifted into
    controller time, cross-host FLOW arrows following KV ships and
    failover hops (:meth:`FleetTelemetry.chrome_trace`);
  - one Prometheus exposition with ``host=`` labels on every worker
    series (:meth:`FleetTelemetry.prometheus_text`) — the real-bucket
    histograms exist precisely so ``histogram_quantile(sum(rate(
    x_bucket[5m])) by (le))`` aggregates across a fleet, and
    :func:`quantile_from_buckets` is that aggregation done locally;
  - one fleet-wide why_slow (:meth:`FleetTelemetry.why_slow`): worker
    hop detail grafted onto the controller's routing skeleton, all
    seven phases (queue/prefill/ship/decode/stall/preempted/hops)
    exact, and the straggler HOST named.

Telemetry loss NEVER blocks serving: a pull that fails (partition,
timeout, the ``serve.dist.telemetry`` fault site) degrades the host to
a typed ``stale`` marker — last-known data stays readable, health says
so, and the serving RPC stream is untouched.  A host that is retired
or replaced is REMOVED (:meth:`FleetTelemetry.remove_host`): PR 15's
retire-unregisters contract extended across the boundary — a dead
host's series leave the exposition instead of freezing.

Everything here is pure data plumbing: no serve imports, injectable
clocks, synthetic-input friendly (the tests drive it with fake skewed
clocks and hand-built dumps).
"""

from __future__ import annotations

import copy
import json
import math
import time

from ..utils.metrics import percentile
from . import requests as _requests
from . import trace as _trace
from .registry import registry as _registry

__all__ = ["ClockSync", "FleetTelemetry", "dump_registry",
           "quantile_from_buckets", "merge_bucket_counts", "install",
           "uninstall", "dist_section"]


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

class ClockSync:
    """One peer's clock relation to ours, from min-RTT ping samples.

    ``offset`` is ``peer_clock - local_clock`` (seconds): a peer
    timestamp maps into local time as ``t_local = t_peer - offset``
    (:meth:`to_local`).  ``rtt`` is the minimum observed round trip and
    ``uncertainty == rtt / 2`` bounds the offset error — the peer read
    its clock somewhere inside the round trip, so the midpoint
    estimate can be wrong by at most half of it.
    """

    __slots__ = ("offset", "rtt", "samples", "_clock")

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.offset = 0.0
        self.rtt = float("inf")
        self.samples = 0

    def sample(self, probe, samples=5):
        """Run ``samples`` round trips; ``probe()`` must return the
        peer's clock reading.  Keeps the minimum-RTT sample (least
        queueing noise — the standard NTP filter).  Returns self."""
        if samples < 1:
            raise ValueError(f"samples must be >= 1, got {samples}")
        for _ in range(samples):
            t0 = self._clock()
            t_peer = probe()
            t1 = self._clock()
            rtt = max(t1 - t0, 0.0)
            if rtt <= self.rtt:
                self.rtt = rtt
                self.offset = t_peer - (t0 + t1) / 2.0
            self.samples += 1
        return self

    @property
    def uncertainty(self) -> float:
        """Worst-case |true offset - estimate|: RTT/2."""
        return self.rtt / 2.0 if math.isfinite(self.rtt) else float("inf")

    def to_local(self, t_peer):
        """Map a peer timestamp into the local clock."""
        return t_peer - self.offset

    def summary(self) -> dict:
        return {"offset_s": self.offset,
                "rtt_s": self.rtt if math.isfinite(self.rtt) else None,
                "uncertainty_s": (self.uncertainty
                                  if math.isfinite(self.rtt) else None),
                "samples": self.samples}


# ---------------------------------------------------------------------------
# registry dumps (the metric half of the telemetry wire schema)
# ---------------------------------------------------------------------------

def dump_registry(reg=None) -> dict:
    """Serialize a registry for the telemetry wire
    (:meth:`MetricsRegistry.dump`): name/kind/labels/help per metric,
    plus value (counter/gauge) or the full cumulative bucket ladder +
    running sum/count + exact nearest-rank quantiles (histogram).
    Shipping the BUCKETS — not the summary — is what lets the
    controller re-expose worker histograms as real TYPE-histogram
    families that ``histogram_quantile`` can aggregate across
    hosts."""
    if reg is None:
        reg = _registry()
    return reg.dump()


def merge_bucket_counts(dumps) -> list:
    """Element-wise sum of cumulative ``[le, count]`` ladders from the
    same histogram family on several hosts (they share
    ``DEFAULT_BUCKETS`` or the family's override, so the ladders
    align).  This IS ``sum(x_bucket) by (le)``."""
    merged = {}
    for b in dumps:
        for le, c in b:
            le = float(le)
            merged[le] = merged.get(le, 0) + c
    return sorted(merged.items())


def quantile_from_buckets(bucket_counts, q) -> float:
    """Prometheus ``histogram_quantile``: linear interpolation inside
    the bucket holding rank ``q * count``.  ``bucket_counts`` is the
    cumulative ``(le, count)`` ladder ending at ``+Inf``.  Returns the
    highest finite bound when the rank lands in the overflow bucket
    (Prometheus returns the same)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    bc = [(float(le), c) for le, c in bucket_counts]
    total = bc[-1][1]
    if total <= 0:
        return float("nan")
    rank = q * total
    prev_le, prev_c = 0.0, 0
    for le, c in bc:
        if c >= rank:
            if math.isinf(le):
                # rank in overflow: the best honest answer is the
                # highest finite bound (prometheus semantics)
                return prev_le if prev_c else float("nan")
            if c == prev_c:
                return le
            return prev_le + (le - prev_le) * (rank - prev_c) / (c - prev_c)
        prev_le, prev_c = le, c
    return bc[-2][0] if len(bc) > 1 else float("nan")


# ---------------------------------------------------------------------------
# the controller-side merge point
# ---------------------------------------------------------------------------

class _Host:
    """Last-known telemetry for one worker host."""

    def __init__(self, host, clock_sync=None, thread=None, pid=None):
        self.host = host
        self.clock = clock_sync          # ClockSync or None (thread mode)
        self.thread = thread             # worker thread name (thread mode)
        self.pid = pid
        self.stale = False
        self.stale_reason = None
        self.last_pull_t = None
        self.pulls = 0
        self.registry = None             # last dump_registry() payload
        self.entries = {}                # rid -> sealed ledger entry (raw)
        self.trace = []                  # drained trace records (raw)

    def offset(self) -> float:
        return self.clock.offset if self.clock is not None else 0.0


class FleetTelemetry:
    """Merge worker telemetry into one trace / exposition / why_slow.

    The fleet drives it: :meth:`host_online` on every supervisor spawn
    (with a fresh :class:`ClockSync`), :meth:`ingest` per successful
    pull, :meth:`mark_stale` per failed one, :meth:`remove_host` on
    retire/replace.  Reads are pure over last-known state and never
    touch the wire."""

    def __init__(self, clock=time.monotonic, fleet="fleet"):
        self._clock = clock
        self.fleet = fleet
        self.hosts = {}           # host id -> _Host, insertion-ordered

    # -- fleet-driven lifecycle ------------------------------------------
    def host_online(self, host, clock_sync=None, thread=None,
                    pid=None):
        """(Re)register a host: a fresh supervisor means fresh clock
        base and fresh series — any predecessor's state is dropped
        first (replace_dead must not freeze the dead process's
        series into the exposition)."""
        self.hosts.pop(host, None)
        self.hosts[host] = _Host(host, clock_sync, thread=thread,
                                 pid=pid)
        return self.hosts[host]

    def remove_host(self, host):
        """Retire-unregisters, across the boundary: the host's series
        leave the exposition and its trace/ledger buffers are
        dropped."""
        self.hosts.pop(host, None)

    def mark_stale(self, host, reason):
        """Telemetry loss (NOT serving loss): keep last-known data,
        flag it typed.  Never raises — a telemetry failure must never
        block serving."""
        h = self.hosts.get(host)
        if h is None:
            h = self.host_online(host)
        h.stale = True
        h.stale_reason = str(reason)

    def ingest(self, host, payload, t=None):
        """Merge one telemetry reply.  Idempotent: ledger entries are
        keyed by request id (latest seal wins), the registry dump
        replaces the previous one wholesale, and trace events carry
        the worker's drain cursor semantics (each event arrives
        exactly once).  A successful pull clears ``stale``."""
        h = self.hosts.get(host)
        if h is None:
            h = self.host_online(host)
        h.stale = False
        h.stale_reason = None
        h.last_pull_t = t if t is not None else self._clock()
        h.pulls += 1
        if payload.get("registry") is not None:
            h.registry = payload["registry"]
        for e in payload.get("ledger") or ():
            rid = e.get("request_id")
            if rid is None:
                continue
            prev = h.entries.get(rid)
            if prev is not None and _seal_key(prev) == _seal_key(e):
                continue  # same seal re-shipped: idempotent
            if prev is None or (_seal_key(e) >= _seal_key(prev)):
                h.entries[rid] = e
        for rec in payload.get("trace") or ():
            h.trace.append(rec)
        if payload.get("pid") is not None:
            h.pid = payload["pid"]
        return h

    # -- merged request timelines ----------------------------------------
    def merged_entries(self, local_entries=None) -> list:
        """One sealed-entry list for the whole fleet, in controller
        time.  Controller entries (the routing skeleton: hop chain,
        replica/host stamps, ship_s) are grafted with worker-side hop
        detail (admission, first token, steps, preemptions — shifted
        by each host's clock offset); worker-only requests ride along
        as-is.  Deep-copies everything: calling twice is idempotent
        and never mutates the live ledgers."""
        if local_entries is None:
            lg = _requests.ledger()
            local_entries = lg.entries() if lg is not None else []
        out = [copy.deepcopy(e) for e in local_entries]
        seen = set()
        for e in out:
            seen.add(e.get("request_id"))
            for hop in e.get("hops") or ():
                if hop.get("host") is None \
                        and hop.get("replica") is not None:
                    hop["host"] = f"w{hop['replica']}"
        by_rid = {e.get("request_id"): e for e in out}
        scratch = _requests.RequestLedger(capacity=1)
        for host, h in self.hosts.items():
            dt = -h.offset()
            for rid, we in h.entries.items():
                we = _shift_entry(copy.deepcopy(we), dt)
                for hop in we.get("hops") or ():
                    if hop.get("host") is None:
                        hop["host"] = host
                ce = by_rid.get(rid)
                if ce is None:
                    seen.add(rid)
                    by_rid[rid] = we
                    out.append(we)
                elif _graft_entry(ce, we, host):
                    try:
                        scratch._finalize(ce)
                    except Exception:
                        pass  # partial worker record: keep the graft
        out.sort(key=lambda e: e.get("t_submit") or 0.0)
        return out

    # -- fleet why_slow ---------------------------------------------------
    def why_slow(self, local_entries=None, top_k=5) -> dict:
        """The fleet-wide ``why_slow``: the per-process attribution
        (queue/prefill/hops + the exact ``ship`` carve-out) computed
        over MERGED entries, plus the all-seven-phase latency
        decomposition and the straggler host
        (:meth:`RequestLedger.why_slow` grew those fields alongside
        this module)."""
        entries = self.merged_entries(local_entries)
        lg = _requests.RequestLedger(capacity=max(len(entries), 1))
        lg._ring = entries
        ws = lg.why_slow(top_k=top_k)
        ws["hosts"] = len(self.hosts)
        ws["stale_hosts"] = sorted(
            h.host for h in self.hosts.values() if h.stale)
        return ws

    # -- federated exposition --------------------------------------------
    def prometheus_text(self) -> str:
        """One exposition for the fleet: every worker series re-emitted
        with a ``host=`` label, TYPE/HELP declared once per family,
        bucket ladders shipped verbatim (so ``x_bucket{le="+Inf"} ==
        x_count`` holds per host series and ``sum() by (le)``
        aggregates), plus federation meta-series: per-host staleness,
        clock offset/rtt, and pull age.  Stale hosts keep their
        last-known series (flagged); REMOVED hosts are simply gone."""
        from .export import _prom_labels, _prom_name, _prom_num
        families = {}   # name -> {"kind", "help", "samples": [...]}
        for host, h in self.hosts.items():
            if h.registry is None:
                continue
            for m in h.registry["metrics"]:
                fam = families.setdefault(m["name"], {
                    "kind": m["kind"], "help": m.get("help", ""),
                    "samples": []})
                labels = [tuple(kv) for kv in m["labels"]]
                labels.append(("host", host))
                # sorted label order makes the federated exposition
                # deterministic across hosts and pulls (diff-able)
                fam["samples"].append((sorted(labels), m))
        lines = []
        for name in sorted(families):
            fam = families[name]
            pname = _prom_name(name)
            decl = pname + "_total" if fam["kind"] == "counter" \
                else pname
            if fam["help"]:
                lines.append(f"# HELP {decl} {fam['help']}")
            lines.append(f"# TYPE {decl} {fam['kind']}")
            for labels, m in fam["samples"]:
                if fam["kind"] == "histogram":
                    for le, c in m["buckets"]:
                        lines.append(
                            pname + "_bucket"
                            + _prom_labels(sorted(
                                labels + [("le", _prom_num(le))]))
                            + " " + _prom_num(c))
                    lines.append(pname + "_sum" + _prom_labels(labels)
                                 + " " + _prom_num(m["sum"]))
                    lines.append(pname + "_count"
                                 + _prom_labels(labels)
                                 + " " + _prom_num(m["count"]))
                else:
                    suffix = ("_total" if fam["kind"] == "counter"
                              else "")
                    lines.append(pname + suffix + _prom_labels(labels)
                                 + " " + _prom_num(m["value"]))
            if fam["kind"] == "histogram":
                lines.append(f"# TYPE {pname}_quantile gauge")
                for labels, m in fam["samples"]:
                    for q in (0.5, 0.99):
                        lines.append(
                            pname + "_quantile"
                            + _prom_labels(sorted(
                                labels + [("quantile", q)]))
                            + " " + _prom_num(m.get(f"p{int(q*100)}",
                                                    float("nan"))))
        now = self._clock()
        lines.append("# HELP singa_tpu_federation_stale 1 while the "
                     "host's telemetry channel is lost (typed stale "
                     "marker; serving is unaffected)")
        lines.append("# TYPE singa_tpu_federation_stale gauge")
        for host, h in self.hosts.items():
            lines.append("singa_tpu_federation_stale"
                         + _prom_labels([("host", host)])
                         + " " + ("1" if h.stale else "0"))
        lines.append("# TYPE singa_tpu_federation_clock_offset_seconds"
                     " gauge")
        lines.append("# TYPE singa_tpu_federation_clock_rtt_seconds "
                     "gauge")
        lines.append("# TYPE singa_tpu_federation_pull_age_seconds "
                     "gauge")
        for host, h in self.hosts.items():
            lbl = _prom_labels([("host", host)])
            if h.clock is not None:
                lines.append("singa_tpu_federation_clock_offset_"
                             "seconds" + lbl + " "
                             + _prom_num(h.clock.offset))
                if math.isfinite(h.clock.rtt):
                    lines.append("singa_tpu_federation_clock_rtt_"
                                 "seconds" + lbl + " "
                                 + _prom_num(h.clock.rtt))
            if h.last_pull_t is not None:
                lines.append("singa_tpu_federation_pull_age_seconds"
                             + lbl + " "
                             + _prom_num(max(now - h.last_pull_t,
                                             0.0)))
        return "\n".join(lines) + "\n"

    def merged_histogram(self, name) -> dict:
        """Fleet-level view of one histogram family: per-host cumulative
        ladders summed by ``le`` (``sum(x_bucket) by (le)``), total
        count, and the aggregated p50/p99 via
        :func:`quantile_from_buckets` — the cross-host quantile the
        per-process nearest-rank numbers cannot give."""
        per_host, ladders, count = {}, [], 0
        for host, h in self.hosts.items():
            if h.registry is None:
                continue
            for m in h.registry["metrics"]:
                if m["name"] != name or m["kind"] != "histogram":
                    continue
                per_host.setdefault(host, 0)
                per_host[host] += m["count"]
                ladders.append(m["buckets"])
                count += m["count"]
        merged = merge_bucket_counts(ladders) if ladders else []
        return {
            "name": name,
            "count": count,
            "per_host_counts": per_host,
            "buckets": merged,
            "p50": (quantile_from_buckets(merged, 0.5)
                    if merged else None),
            "p99": (quantile_from_buckets(merged, 0.99)
                    if merged else None),
        }

    # -- merged Chrome trace ---------------------------------------------
    def chrome_trace(self, events=None, requests=None,
                     metadata=None) -> dict:
        """One Chrome-trace document for the whole fleet.

        pid 0 is the controller's subsystem tracks, pid 1 the merged
        per-request tracks (hop flow arrows included), and pids 10+
        one per HOST: worker trace events shifted into controller time
        by each host's clock offset (thread-mode worker events, which
        already share the controller clock, are routed to their host's
        pid by thread name instead).  Cross-host FLOW arrows (``ph:
        s``/``f`` pairs spanning two host pids) follow every KV ship
        and failover hop whose source and destination hosts differ —
        in Perfetto a disaggregated request reads as an arrow from the
        prefill host into the decode host."""
        from . import export as _export
        if events is None:
            events = _trace.events()
        if requests is None:
            requests = self.merged_entries()
        hosts = list(self.hosts)
        pid_of = {h: 10 + i for i, h in enumerate(hosts)}
        thread_host = {h.thread: h.host for h in self.hosts.values()
                       if h.thread}
        ctrl, per_host = [], {h: [] for h in hosts}
        for rec in events:
            hh = thread_host.get(rec.get("tid"))
            if hh is not None:
                per_host[hh].append((rec, 0.0))  # same process clock
            else:
                ctrl.append(rec)
        for host, h in self.hosts.items():
            dt = -h.offset()
            for rec in h.trace:
                per_host[host].append((rec, dt))
        doc = _export.chrome_trace(ctrl, metadata=metadata,
                                   requests=requests)
        ev = doc["traceEvents"]
        flows = 0
        for host in hosts:
            pid = pid_of[host]
            ev.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"host {host}"}})
            ev.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": "cross-host"}})
            cats = []
            for rec, _ in per_host[host]:
                if rec["cat"] not in cats:
                    cats.append(rec["cat"])
            tid_of = {c: i + 1 for i, c in enumerate(cats)}
            for c, tid in tid_of.items():
                ev.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": c}})
            for rec, dt in per_host[host]:
                args = dict(rec["args"] or {})
                args["thread"] = rec["tid"]
                args["host"] = host
                e2 = {"name": rec["name"], "cat": rec["cat"],
                      "ph": rec["ph"], "pid": pid,
                      "tid": tid_of[rec["cat"]],
                      "ts": (rec["ts"] + dt) * 1e6, "args": args}
                if rec["ph"] == "X":
                    e2["dur"] = rec["dur"] * 1e6
                else:
                    e2["s"] = "t"
                ev.append(e2)
        # cross-host flow arrows: one s/f pair per hop boundary whose
        # source and destination hosts differ, drawn between the two
        # host pids (KV ships span their measured wire time)
        fid = 1 << 20  # disjoint from request_trace_events' flow ids
        for e in requests:
            hops = e.get("hops") or []
            for j in range(1, len(hops)):
                src = hops[j - 1].get("host")
                dst = hops[j].get("host")
                if src is None or dst is None or src == dst:
                    continue
                if src not in pid_of or dst not in pid_of:
                    continue
                via = hops[j].get("via") or "hop"
                t1 = hops[j]["t_submit"] * 1e6
                ship_s = hops[j].get("ship_s")
                t0 = t1 - (ship_s * 1e6 if via == "kv_ship" and ship_s
                           else 1.0)
                fid += 1
                args = {"request": e.get("request_id"), "via": via,
                        "src_host": src, "dst_host": dst}
                ev.append({"name": via, "cat": "fleet", "ph": "s",
                           "pid": pid_of[src], "tid": 0, "id": fid,
                           "ts": t0, "args": args})
                ev.append({"name": via, "cat": "fleet", "ph": "f",
                           "bp": "e", "pid": pid_of[dst], "tid": 0,
                           "id": fid, "ts": t1, "args": args})
                flows += 1
        doc["otherData"]["hosts"] = hosts
        doc["otherData"]["cross_host_flows"] = flows
        return doc

    def write_chrome_trace(self, path, events=None, requests=None,
                           metadata=None) -> int:
        doc = self.chrome_trace(events, requests, metadata)
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        return len(doc["traceEvents"])

    def write_request_log(self, path, local_entries=None) -> int:
        """Merged sealed entries as strict JSONL (the bench
        ``--request-log`` artifact, fleet-wide)."""
        from .export import json_sanitize
        n = 0
        with open(path, "w") as f:
            for e in self.merged_entries(local_entries):
                f.write(json.dumps(json_sanitize(e), default=str,
                                   allow_nan=False) + "\n")
                n += 1
        return n

    # -- health -----------------------------------------------------------
    def section(self, top_k=3) -> dict:
        """``health_report()["serve"]["dist"]``: per-host telemetry
        status (clock model, staleness, pull age) + the fleet-wide
        why_slow."""
        now = self._clock()
        hosts = {}
        for host, h in self.hosts.items():
            hosts[host] = {
                "stale": h.stale,
                "stale_reason": h.stale_reason,
                "pulls": h.pulls,
                "last_pull_age_s": (max(now - h.last_pull_t, 0.0)
                                    if h.last_pull_t is not None
                                    else None),
                "pid": h.pid,
                "clock": (h.clock.summary() if h.clock is not None
                          else None),
                "ledger_entries": len(h.entries),
                # per-host step anatomy from the shipped registry
                # dump (observe.stepprof on the worker): mean device
                # bubble + step count, None until the host ships
                # serve.step.* series — the straggler question
                # "which HOST's engine is host-bound" answered here
                "step_anatomy": _host_step_anatomy(h.registry),
            }
        return {
            "enabled": True,
            "fleet": self.fleet,
            "hosts": hosts,
            "stale_hosts": sorted(h for h, d in hosts.items()
                                  if d["stale"]),
            "why_slow": self.why_slow(top_k=top_k),
        }


def _host_step_anatomy(dump):
    """Mean device-bubble fraction + step count for one host, from
    its shipped registry dump (pure dict work — the worker's
    ``serve.step.{bubble_frac,wall_s}`` running sums/counts summed
    across its engine labels).  None until the host ships the
    families (profiler off, or no pull yet)."""
    if not dump:
        return None
    bub_sum = bub_n = steps = 0
    for m in dump.get("metrics", ()):
        if m.get("kind") != "histogram":
            continue
        if m["name"] == "serve.step.bubble_frac":
            bub_sum += m.get("sum", 0.0)
            bub_n += m.get("count", 0)
        elif m["name"] == "serve.step.wall_s":
            steps += m.get("count", 0)
    if bub_n == 0:
        return None
    return {"steps": steps, "bubble_frac": bub_sum / bub_n}


def _seal_key(e):
    """Order two seals of the same request id: later retire wins (a
    rejected-then-resurrected request's final seal replaces the
    interim one); equal keys mean the same seal re-shipped."""
    t = e.get("t_retire")
    return (0.0 if t is None else t, e.get("outcome") or "")


_SHIFT_HOP_TS = ("t_submit", "t_admit", "t_first_token")


def _shift_entry(e, dt):
    """Shift every absolute timestamp in a sealed entry by ``dt``
    seconds (worker clock -> controller clock; durations are
    invariant).  Mutates and returns ``e`` (callers pass a copy)."""
    for k in ("t_submit", "t_retire"):
        if e.get(k) is not None:
            e[k] += dt
    for hop in e.get("hops") or ():
        for k in _SHIFT_HOP_TS:
            if hop.get(k) is not None:
                hop[k] += dt
        for ch in hop.get("chunks") or ():
            ch[0] += dt
        for st in hop.get("steps") or ():
            st[0] += dt
        for pre in hop.get("preemptions") or ():
            if pre[0] is not None:
                pre[0] += dt
            if len(pre) > 1 and pre[1] is not None:
                pre[1] += dt
        if hop.get("reject") and hop["reject"].get("t") is not None:
            hop["reject"]["t"] += dt
    return e


_GRAFT_FIELDS = ("t_admit", "admit_kind", "hit_tokens", "slot",
                 "chunks", "t_first_token", "steps", "tokens",
                 "preemptions")


def _graft_entry(ce, we, host) -> bool:
    """Fill the controller entry's hop skeleton with the worker's
    engine-side detail (process mode: the controller mirror only has
    submit/retire).  Hops match by host — the worker's record can only
    describe work that ran THERE.  Returns True when anything landed
    (the caller re-finalizes ttft/phases)."""
    grafted = False
    whops = [h for h in we.get("hops") or ()]
    if not whops:
        return False
    wi = 0
    for hop in ce.get("hops") or ():
        if hop.get("host") != host:
            continue
        if wi >= len(whops):
            break
        wh = whops[wi]
        wi += 1
        for k in _GRAFT_FIELDS:
            v = wh.get(k)
            if v in (None, [], 0) or hop.get(k) not in (None, [], 0):
                continue
            hop[k] = v
            grafted = True
    if grafted and ce.get("tokens_out") in (None, 0):
        ce["tokens_out"] = we.get("tokens_out")
    return grafted


# ---------------------------------------------------------------------------
# module-global install point (health_report reads through here)
# ---------------------------------------------------------------------------

_active_ft = None


def install(ft):
    """Make ``ft`` the fleet telemetry ``health_report()`` reads (a
    DistFleet with federation on installs itself)."""
    global _active_ft
    _active_ft = ft
    return ft


def uninstall(ft=None):
    """Detach (``ft`` given: only if it is still the installed one —
    two fleets in one process must not uninstall each other)."""
    global _active_ft
    if ft is None or _active_ft is ft:
        _active_ft = None


def dist_section() -> dict:
    """``health_report()["serve"]["dist"]``: always a dict with an
    ``enabled`` key; live content while a federated DistFleet is
    installed."""
    if _active_ft is None:
        return {"enabled": False}
    try:
        return _active_ft.section()
    except Exception as e:  # telemetry must never fail a health read
        return {"enabled": True, "error": repr(e)}
