"""singa_tpu.observe — unified tracing + metrics for train, serve, comms.

The telemetry layer of the ROADMAP north star: one event model
(``trace.py`` spans/instants), one process-wide metrics surface
(``registry.py`` Counter/Gauge/Histogram, adopting the
``utils.metrics`` percentile machinery), and three exporters
(``export.py``: JSONL, Chrome trace-event JSON for Perfetto,
Prometheus text).  Instrumented out of the box: graph-mode compile vs
replay (``model._GraphRunner``), optimizer updates (``opt``),
collectives (``parallel.communicator``), checkpoints (``snapshot`` /
``Model.save_states``), and the serving engine's prefill / decode /
retire loop (``serve.engine``, whose ``EngineStats`` registers its
counters here).

Tracing is OFF by default and costs one flag check per site when off;
the registry is always on (counter bumps, vLLM-style).  See
docs/OBSERVABILITY.md.

Since the request-tracing round, ``requests.py`` adds a per-REQUEST
lifecycle ledger over the serve stack: one timeline per
``GenerationRequest`` (queue wait, cold/warm admission, per-step
emission, supervisor-restart and fleet-failover hops), a bounded JSONL
request log, per-request Chrome-trace tracks with hop flow arrows, and
the ``health_report()["serve"]["why_slow"]`` tail-latency attribution
(``requests.enable()`` — off by default, one flag read per hook when
off).

Since PR 3 there is also an ACTIVE layer over the passive one
(``monitor.py`` + ``health.py``): an always-on flight recorder with
crash bundles (``monitor.install_crash_handler``), MFU/goodput
accounting against a per-backend peak-FLOPs table, a hang/anomaly
watchdog fed by heartbeats from the graph runner and the serve decode
loop, declarative serve SLOs (``SLO``), and the one-call
:func:`health_report` summary.  See docs/OBSERVABILITY.md.

    from singa_tpu import observe
    observe.enable()
    observe.monitor.start()          # recorder + watchdog + MFU
    ...train / serve...
    observe.export.write_chrome_trace("/tmp/trace.json")
    print(observe.export.prometheus_text())
    print(observe.health_report()["train"]["mfu"])
"""

from . import export  # noqa: F401
from . import trace  # noqa: F401
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, registry)
from .trace import (clear, disable, drain, dropped,  # noqa: F401
                    enable, event, events, is_enabled, set_max_events,
                    span, traced)
from . import stepprof  # noqa: F401  (step-anatomy profiler:
#                                      host/device attribution)
from .stepprof import StepProfiler  # noqa: F401
from . import monitor  # noqa: F401  (imports trace/registry only)
from . import requests  # noqa: F401  (per-request lifecycle ledger)
from .requests import RequestLedger  # noqa: F401
from . import timeseries  # noqa: F401  (windowed telemetry rings)
from .timeseries import WindowedFamily, WindowRing  # noqa: F401
from . import slo  # noqa: F401  (multi-window burn-rate alerting)
from .slo import BurnRule, SLOPolicy  # noqa: F401
from . import federate  # noqa: F401  (cross-host merge: clocks,
#                                      traces, metrics, why_slow)
from .federate import ClockSync, FleetTelemetry  # noqa: F401
from . import health  # noqa: F401
from .health import SLO, health_report  # noqa: F401
