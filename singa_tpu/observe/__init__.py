"""singa_tpu.observe — unified tracing + metrics for train, serve, comms.

The telemetry layer of the ROADMAP north star: one event model
(``trace.py`` spans/instants), one process-wide metrics surface
(``registry.py`` Counter/Gauge/Histogram, adopting the
``utils.metrics`` percentile machinery), and three exporters
(``export.py``: JSONL, Chrome trace-event JSON for Perfetto,
Prometheus text).  Instrumented out of the box: graph-mode compile vs
replay (``model._GraphRunner``), optimizer updates (``opt``),
collectives (``parallel.communicator``), checkpoints (``snapshot`` /
``Model.save_states``), and the serving engine's prefill / decode /
retire loop (``serve.engine``, whose ``EngineStats`` registers its
counters here).

Tracing is OFF by default and costs one flag check per site when off;
the registry is always on (counter bumps, vLLM-style).  See
docs/OBSERVABILITY.md.

    from singa_tpu import observe
    observe.enable()
    ...train / serve...
    observe.export.write_chrome_trace("/tmp/trace.json")
    print(observe.export.prometheus_text())
"""

from . import export  # noqa: F401
from . import trace  # noqa: F401
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, registry)
from .trace import (clear, disable, drain, enable, event,  # noqa: F401
                    events, is_enabled, set_max_events, span, traced)
