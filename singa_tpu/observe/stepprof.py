"""Step-anatomy profiler: host/device attribution for every
``engine.step()``.

ROADMAP item 5 ("overlap host scheduling with device compute") needs a
measuring stick before the surgery: the RequestLedger attributes
per-REQUEST phases (queue/prefill/decode/stall), but nothing measures
where one STEP's wall time goes — how much is host bookkeeping between
dispatches (the device-idle *bubble* the overlap work must close) and
how much is the device actually executing.  This module is that
microscope:

* **host segments** — clock fences at the existing seams in
  ``serve/engine.py`` decompose the step wall into named host
  segments: ``schedule`` (the scheduling pass), ``admit`` (one
  admission's host work), ``prefix_lookup`` (radix-cache probes),
  ``dispatch`` (building inputs + launching an executable),
  ``sync`` (host-side copies after the device is done), ``emit``
  (token emission + callbacks), ``retire`` (slot teardown),
  ``ledger`` (RequestLedger hooks).  Fences nest; accounting is
  EXCLUSIVE (a retire inside the emit loop is retire time, never
  double-counted as emit), and unfenced host time lands in
  ``other`` — so the segments always sum to the wall exactly, the
  RequestLedger's seal-time idiom.
* **device time** — one hook at the executor seam (``engine._x``:
  ``_LocalExec``, ``TPExecutor``, and the ep/pp executors all route
  through it, so one wrapper covers every parallelism mode) records
  dispatch→``block_until_ready`` on each dispatch's output.  Async
  dispatch is therefore credited, not hidden: host work done while
  the device runs overlaps the device window instead of extending
  it.  ``bubble_frac = (wall - device) / wall`` is the fraction of
  the step during which the device sat idle — the item-5 metric.
* **zero cost when off** — every fence site is ONE module-flag read
  (``if stepprof._active:``), the trace.py discipline: no allocation,
  no clock call, nothing enters jitted code (the hook only adds a
  ``block_until_ready`` on already-materialized outputs, so the
  recompile pin holds with the profiler ON).

Publication surfaces:

* registry: ``serve.step.{wall_s,host_s,device_s}{engine=}`` and
  ``serve.step.segment_s{engine=,segment=}`` histograms on a dedicated
  100µs–5s ladder (:data:`STEP_BUCKETS` — the default request ladder
  is far too coarse for 5–50ms steps), plus
  ``serve.step.bubble_frac{engine=}`` on a 0–1 fraction ladder.
  Registered lazily per engine label; an engine's close
  (:func:`forget_engine`) removes its series — the retire-unregisters
  contract.
* trace: one ``cat="step.host"`` COMPLETE record per step (segment
  fractions in args) and one ``cat="step.device"`` record per device
  window, emitted through ``trace._emit`` whenever tracing or the
  flight-recorder ring is live — so worker step anatomy rides the
  existing cross-host trace federation (observe/federate.py) and
  shows up as two lanes per host pid in the merged Chrome trace.
* ring: the last N full step records (per-piece host intervals +
  device windows) for the dual-lane local Chrome trace
  (``export.chrome_trace(steps=...)``).
* health: :func:`section` → ``health_report()["serve"]
  ["step_anatomy"]``; :func:`why_slow_summary` rides the why_slow
  section; :func:`culprit` feeds the Watchdog so a step-time anomaly
  names host-vs-device.

Profiler state is MODULE-level (like trace/monitor): an
``EngineSupervisor`` restart builds a fresh engine under the same
profiler, whose fresh ``engine=`` label starts fresh series while the
dead engine's are removed.
"""

from __future__ import annotations

import collections
import threading
import time

from .registry import registry as _registry
from . import trace as _trace

__all__ = ["StepProfiler", "enable", "disable", "active", "profiler",
           "section", "why_slow_summary", "culprit", "records",
           "forget_engine", "SEGMENTS", "STEP_BUCKETS",
           "FRACTION_BUCKETS"]

#: segment taxonomy (docs/OBSERVABILITY.md "Step anatomy"): the named
#: host segments, the device-execution windows, and the unfenced
#: remainder.  Fractions over these sum to 1 per step by construction.
SEGMENTS = ("schedule", "admit", "prefix_lookup", "dispatch", "device",
            "sync", "emit", "retire", "ledger", "other")

#: dedicated step-latency ladder: 100µs–5s.  registry.DEFAULT_BUCKETS
#: starts at 1ms and tops at 2min — the request ladder, far too coarse
#: for 5–50ms steps.
STEP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

#: bubble_frac is a ratio in [0, 1]; a time ladder would be nonsense
FRACTION_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6,
                    0.7, 0.8, 0.9, 0.95, 0.99, 1.0)

# Module-global fast path, mirroring trace._active: `if not
# stepprof._active: <skip>` is the ENTIRE disabled cost of a fence
# site.  _prof is non-None exactly while _active is True.
_active = False
_prof = None
_tls = threading.local()

_block_until_ready = None  # lazy jax import (observe stays jax-free
#                            until a profiled dispatch actually runs)


def _block(out):
    global _block_until_ready
    if _block_until_ready is None:
        import jax
        _block_until_ready = jax.block_until_ready
    return _block_until_ready(out)


def enable(clock=None, ring=512, reg=None) -> "StepProfiler":
    """Attach a fresh process-wide profiler and turn the fences on.
    ``clock``: ``() -> float`` seconds — pass the trace clock when
    both are on so step-anatomy trace records share its time base
    (the dist worker does; offsets then correct both together).
    ``ring`` bounds the per-step record buffer."""
    global _active, _prof
    _prof = StepProfiler(clock=clock, ring=ring, reg=reg)
    _active = True
    return _prof


def disable(unregister=True):
    """Turn the fences off and detach.  ``unregister=True`` (default)
    also removes every ``serve.step.*`` series the profiler created —
    the retire-unregisters contract; pass False to keep them readable
    (export after disable)."""
    global _active, _prof
    p, _prof = _prof, None
    _active = False
    _tls.cur = None
    if p is not None and unregister:
        p.unregister()


def active() -> bool:
    return _active


def profiler():
    """The live profiler, or None when off."""
    return _prof


def forget_engine(label):
    """Remove a closed engine's ``serve.step.*{engine=label}`` series
    (``engine._release_everything`` calls this): a supervisor-rebuilt
    engine's fresh label must not leave the dead one's series frozen
    in the exposition.  Safe no-op when the profiler is off."""
    if _prof is not None:
        _prof.forget_engine(label)


# -- fences (serve/engine.py calls these, each behind one _active
#    read; all are safe no-ops when no step is open on this thread) --

def begin(engine, step=None):
    p = _prof
    if p is not None:
        p.step_begin(engine, step=step)


def end():
    st = getattr(_tls, "cur", None)
    _tls.cur = None
    if st is not None and st.owner is _prof and _prof is not None:
        _prof._finish(st)


def abort():
    """Drop the open step record (the engine's failure path: a step
    that raised has no meaningful anatomy)."""
    _tls.cur = None


def begin_quantum(engine, step=None) -> bool:
    """Open a step for an out-of-``step()`` work quantum — a prefix
    BUILD chunk on a disaggregated prefill specialist, whose engine
    never runs the decode step loop but whose dispatches are exactly
    the host/device anatomy this profiler exists to expose.  No-op
    (returns False) when a step is already open on this thread — a
    build driven from inside ``step()`` stays attributed to that
    step.  The caller pairs True with :func:`end` / :func:`abort`."""
    p = _prof
    if p is None or getattr(_tls, "cur", None) is not None:
        return False
    p.step_begin(engine, step=step)
    return True


def push(name):
    st = getattr(_tls, "cur", None)
    if st is not None:
        st.push(name)


def pop():
    st = getattr(_tls, "cur", None)
    if st is not None:
        st.pop()


def timed_dispatch(fn, a, kw):
    """The executor-seam hook (``engine._ProfExec``): time the host
    dispatch (building inputs + launching) and the device window
    (dispatch return → ``block_until_ready`` on the output).  The
    block is the ONLY added work — it runs on already-dispatched
    outputs, so nothing new enters jitted code and the recompile pin
    holds.  Outside an open step (e.g. a prefix build between steps)
    the call passes straight through."""
    st = getattr(_tls, "cur", None)
    if st is None:
        return fn(*a, **kw)
    st.push("dispatch")
    out = fn(*a, **kw)
    st.pop()
    st.push("device")
    _block(out)
    t0, dur = st.pop()
    st.dev += dur
    st.dev_windows.append((t0, dur))
    return out


# -- health/monitor read surface --------------------------------------

def section() -> dict:
    """``health_report()["serve"]["step_anatomy"]``: always a dict
    with an ``enabled`` key, so dashboards and the CI gate can assert
    on it unconditionally."""
    if _prof is None:
        return {"enabled": False}
    return _prof.section()


def why_slow_summary():
    """The compact step-anatomy rider on ``why_slow``: overall
    host/device split, the dominant host segment, and the culprit
    verdict.  None when the profiler is off or has no steps."""
    if _prof is None:
        return None
    return _prof.why_slow_summary()


def culprit(source=None):
    """The Watchdog feed: host-vs-device attribution for the LAST
    completed step of the engine behind heartbeat ``source``
    (``serve.e<label>``), or of the most recent step when the source
    doesn't parse.  None when the profiler is off or has no record."""
    if _prof is None:
        return None
    return _prof.culprit(source)


def records() -> list:
    """Snapshot of the per-step ring (for the dual-lane Chrome
    trace exporter)."""
    if _prof is None:
        return []
    return list(_prof._ring)


# -- the profiler ------------------------------------------------------

class _StepState:
    """One step's open record: an exclusive-time segment stack plus
    the device windows.  Allocated only while the profiler is ON."""

    __slots__ = ("owner", "engine", "step", "t0", "last", "stack",
                 "seg", "pieces", "dev", "dev_windows", "clock")

    def __init__(self, owner, engine, step, clock):
        self.owner = owner
        self.engine = engine
        self.step = step
        self.clock = clock
        self.t0 = self.last = clock()
        self.stack = []
        self.seg = {}
        self.pieces = []       # (segment, t_start, dur) host intervals
        self.dev = 0.0
        self.dev_windows = []  # (t_start, dur) device-busy intervals

    def push(self, name):
        now = self.clock()
        if self.stack:
            # the parent's elapsed-so-far is the parent's, exclusively
            cur = self.stack[-1]
            dt = now - self.last
            self.seg[cur] = self.seg.get(cur, 0.0) + dt
            self.pieces.append((cur, self.last, dt))
        self.stack.append(name)
        self.last = now

    def pop(self):
        if not self.stack:
            return (self.last, 0.0)
        now = self.clock()
        name = self.stack.pop()
        t0, dt = self.last, now - self.last
        self.seg[name] = self.seg.get(name, 0.0) + dt
        self.pieces.append((name, t0, dt))
        self.last = now
        return (t0, dt)


class StepProfiler:
    """Per-step host/device time attribution (module docstring).

    Single-writer per thread (each engine's step loop is
    single-threaded; concurrent engines on different threads each
    carry their own open step via a thread-local)."""

    def __init__(self, clock=None, ring=512, reg=None):
        self._clock = clock if clock is not None else time.perf_counter
        self._reg = reg if reg is not None else _registry()
        self._ring = collections.deque(maxlen=int(ring))
        self._metrics = {}      # engine label -> {"wall": h, ...}
        self._seg_metrics = {}  # (label, segment) -> Histogram
        self._registered = []
        self._agg = {}          # label -> {"steps", "wall_s",
        #                                   "device_s", "seg": {...}}
        self.steps = 0

    # -- recording -------------------------------------------------------
    def step_begin(self, engine, step=None):
        _tls.cur = _StepState(self, engine, step, self._clock)

    def _finish(self, st):
        now = self._clock()
        while st.stack:          # a dangling fence closes at step end
            st.pop()
        wall = max(now - st.t0, 0.0)
        seg = st.seg
        other = wall - sum(seg.values())
        if other > 0.0:
            seg["other"] = seg.get("other", 0.0) + other
        device = st.dev
        host = max(wall - device, 0.0)
        bubble = (host / wall) if wall > 0.0 else 0.0
        label = st.engine
        agg = self._agg.get(label)
        if agg is None:
            agg = self._agg[label] = {"steps": 0, "wall_s": 0.0,
                                      "device_s": 0.0, "seg": {}}
        agg["steps"] += 1
        agg["wall_s"] += wall
        agg["device_s"] += device
        aseg = agg["seg"]
        for k, v in seg.items():
            aseg[k] = aseg.get(k, 0.0) + v
        self._publish(label, wall, host, device, bubble, seg)
        rec = {"engine": label, "step": st.step, "t0": st.t0,
               "wall_s": wall, "host_s": host, "device_s": device,
               "bubble_frac": bubble, "segments": dict(seg),
               "pieces": st.pieces, "device_windows": st.dev_windows}
        self._ring.append(rec)
        self.steps += 1
        if _trace._active:
            # ride the trace buffer/ring (and, on a dist worker, the
            # trace federation): one host-lane record per step, one
            # device-lane record per window — per-host dual lanes in
            # the merged Chrome trace come from exactly these
            tid = threading.current_thread().name
            _trace._emit({
                "name": f"step/e{label}", "cat": "step.host",
                "ph": "X", "ts": st.t0, "dur": wall, "tid": tid,
                "depth": 0, "parent": None,
                "args": {"engine": label, "step": st.step,
                         "bubble_frac": round(bubble, 4),
                         "device_s": device,
                         "segments": {k: round(v, 6)
                                      for k, v in seg.items()}}})
            for t0w, dw in st.dev_windows:
                _trace._emit({
                    "name": f"device/e{label}", "cat": "step.device",
                    "ph": "X", "ts": t0w, "dur": dw, "tid": tid,
                    "depth": 0, "parent": None,
                    "args": {"engine": label, "step": st.step}})

    def _publish(self, label, wall, host, device, bubble, seg):
        m = self._metrics.get(label)
        if m is None:
            reg = self._reg
            m = {
                "wall": reg.histogram(
                    "serve.step.wall_s",
                    help="engine.step() wall seconds",
                    buckets=STEP_BUCKETS, engine=label),
                "host": reg.histogram(
                    "serve.step.host_s",
                    help="host-side step seconds (wall - device)",
                    buckets=STEP_BUCKETS, engine=label),
                "device": reg.histogram(
                    "serve.step.device_s",
                    help="device-busy step seconds (dispatch -> "
                         "block_until_ready, summed per window)",
                    buckets=STEP_BUCKETS, engine=label),
                "bubble": reg.histogram(
                    "serve.step.bubble_frac",
                    help="device-idle fraction of the step wall",
                    buckets=FRACTION_BUCKETS, engine=label),
            }
            self._metrics[label] = m
            self._registered += list(m.values())
        m["wall"].observe(wall)
        m["host"].observe(host)
        m["device"].observe(device)
        m["bubble"].observe(bubble)
        for name, v in seg.items():
            h = self._seg_metrics.get((label, name))
            if h is None:
                h = self._reg.histogram(
                    "serve.step.segment_s",
                    help="per-segment host/device step seconds",
                    buckets=STEP_BUCKETS, engine=label, segment=name)
                self._seg_metrics[(label, name)] = h
                self._registered.append(h)
            h.observe(v)

    # -- lifecycle -------------------------------------------------------
    def forget_engine(self, label):
        dead = list(self._metrics.get(label, {}).values())
        dead += [h for (lbl, _), h in self._seg_metrics.items()
                 if lbl == label]
        if dead:
            self._reg.remove(*dead)
            self._registered = [m for m in self._registered
                                if m not in dead]
        self._metrics.pop(label, None)
        for key in [k for k in self._seg_metrics if k[0] == label]:
            del self._seg_metrics[key]

    def unregister(self):
        if self._registered:
            self._reg.remove(*self._registered)
            self._registered = []
        self._metrics = {}
        self._seg_metrics = {}

    # -- reads -----------------------------------------------------------
    def section(self) -> dict:
        engines = {}
        for label, agg in self._agg.items():
            denom = sum(agg["seg"].values())
            wall = agg["wall_s"]
            n = agg["steps"]
            engines[label] = {
                "steps": n,
                "wall_s_total": wall,
                "wall_s_mean": wall / n if n else 0.0,
                "device_s_total": agg["device_s"],
                "host_s_total": max(wall - agg["device_s"], 0.0),
                "bubble_frac": (max(wall - agg["device_s"], 0.0)
                                / wall if wall > 0 else 0.0),
                # fractions over ONE denominator (the summed segment
                # chain) — they sum to 1 up to float rounding, the
                # ledger's exact-arithmetic idiom
                "fractions": ({k: v / denom
                               for k, v in sorted(agg["seg"].items())}
                              if denom > 0 else {}),
            }
        return {"enabled": True, "steps": self.steps,
                "engines": engines,
                "why_slow": self.why_slow_summary()}

    def why_slow_summary(self):
        wall = sum(a["wall_s"] for a in self._agg.values())
        if wall <= 0.0:
            return None
        device = sum(a["device_s"] for a in self._agg.values())
        host_seg = {}
        for a in self._agg.values():
            for k, v in a["seg"].items():
                if k != "device":
                    host_seg[k] = host_seg.get(k, 0.0) + v
        top = max(host_seg, key=host_seg.get) if host_seg else None
        bubble = max(wall - device, 0.0) / wall
        return {
            "bubble_frac": bubble,
            "device_frac": min(device / wall, 1.0),
            "host_frac": bubble,
            "top_host_segment": top,
            "top_host_segment_frac": (host_seg[top] / wall
                                      if top is not None else 0.0),
            "culprit": "host" if bubble >= 0.5 else "device",
        }

    def culprit(self, source=None):
        label = None
        if isinstance(source, str) and source.startswith("serve.e"):
            label = source[len("serve.e"):]
        for rec in reversed(self._ring):
            if label is not None and rec["engine"] != label:
                continue
            host_seg = {k: v for k, v in rec["segments"].items()
                        if k != "device"}
            top = (max(host_seg, key=host_seg.get)
                   if host_seg else None)
            return {
                "culprit": ("host" if rec["bubble_frac"] >= 0.5
                            else "device"),
                "bubble_frac": round(rec["bubble_frac"], 4),
                "host_s": rec["host_s"],
                "device_s": rec["device_s"],
                "top_host_segment": top,
            }
        return None
