"""Tape-based autograd, API-shaped after the reference's
``python/singa/autograd.py`` (~4.5k LoC, unverified — SURVEY.md §2.2/§3.2).

Reference behavior being rebuilt:
  * ``Operation`` base class with ``forward(*xs)`` / ``backward(*dys)``
    over raw backend tensors; ``__call__`` records a ``src`` edge list when
    ``training`` is on.
  * ``backward(y, dy)``: dependency-counted reverse-topological walk over
    ``Operation.src`` that **yields** ``(param_tensor, grad_tensor)`` pairs
    as each gradient becomes final — a generator, so ``opt.DistOpt`` can
    overlap all-reduce of early grads with backward of later layers
    (SURVEY.md §3.2: "the generator design is load-bearing").
  * dozens of concrete ops (ReLU, Matmul/Gemm, SoftMax, CrossEntropy,
    Conv2d, BatchNorm2d, Pooling, RNN, reshape ops, ...) each with a
    hand-written VJP calling cuDNN/cuBLAS kernels.

TPU-native design: an op's forward is a **pure jnp/lax function** and its
backward is ``jax.vjp`` of that function — XLA differentiates the same
program it compiles, so hand-written VJPs (and their cuDNN mirror-kernel
bookkeeping) disappear.  The tape itself is kept because SINGA's public
API (``autograd.backward`` generator, ``Operation`` subclassing, stateful
handles) is defined in terms of it; under graph mode the entire
tape-record + walk executes *inside* a ``jax.jit`` trace, so the runtime
cost of the Python walk is paid once at compile time (the reference pays
its scheduler dispatch every iteration).
"""

from __future__ import annotations

from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from . import amp
from .tensor import Tensor, _wrap, _raw
from .device import get_default_device

# module-level training flag, same contract as reference autograd.training
training = False

# export-taping flag: sonnx.to_onnx tapes one training-mode forward to
# build the graph; ops with training-time side effects (BN running-stat
# updates) must treat that pass as pure
exporting = False


def set_training(flag: bool):
    global training
    training = bool(flag)


def set_exporting(flag: bool):
    global exporting
    exporting = bool(flag)


class Operation:
    """One differentiable op instance; records tape edges when training.

    Subclasses implement ``forward(*xs)`` over raw jax arrays and either
    implement ``backward(*dys)`` explicitly (reference style) or set
    ``self.grad_fn`` inside ``forward`` (jax.vjp style; see ``_Func``).
    """

    op_count = 0

    def __init__(self, name=None):
        if name is None:
            name = f"{type(self).__name__}#{Operation.op_count}"
            Operation.op_count += 1
        self.name = name
        self.src = []
        self.y_id2idx = {}
        self.requires_grad = False

    def __call__(self, *xs):
        return self._do_forward(*xs)

    def _do_forward(self, *xs):
        assert all(isinstance(x, Tensor) for x in xs), (
            f"{self.name}: inputs must be Tensors, got {[type(x) for x in xs]}"
        )
        if training:
            self.src = []
            for x in xs:
                if x.requires_grad and (
                    x.creator is None
                    or (isinstance(x.creator, Dummy)
                        and id(x.data) not in x.creator.y_id2idx)
                ):
                    # leaf: attach a Dummy so multi-consumer grads
                    # accumulate at one node before being yielded.  A stale
                    # Dummy (param array rebound by opt.update since last
                    # step) is replaced.
                    x.creator = Dummy(x)
                # the creator edge is recorded for no-grad inputs too so
                # the sonnx export walk can traverse grad-free graphs;
                # backward() still never descends into them (it only
                # enqueues src ops with requires_grad=True) and never
                # yields them (stores_grad=False)
                self.src.append((x.creator, id(x.data), x,
                                 x.stores_grad if x.requires_grad else False))
            self.requires_grad = any(x.requires_grad for x in xs)
        ys = self.forward(*[x.data for x in xs])
        single = not isinstance(ys, tuple)
        if single:
            ys = (ys,)
        dev = xs[0].device if xs else get_default_device()
        if training:
            self.y_id2idx = {id(y): i for i, y in enumerate(ys)}
            # creator is recorded unconditionally while training (the
            # reference tapes every op; requires_grad only gates gradient
            # flow).  This keeps export (sonnx frontend creator-walk)
            # working for grad-free graphs; backward() already stops at
            # src edges whose op has requires_grad=False.
            outs = tuple(
                Tensor(device=dev, data=y, requires_grad=self.requires_grad,
                       creator=self)
                for y in ys
            )
        else:
            outs = tuple(_wrap(y, dev) for y in ys)
        return outs[0] if single else outs

    def _do_backward(self, *dys):
        dxs = self.backward(*dys)
        if not isinstance(dxs, tuple):
            dxs = (dxs,)
        return dxs

    def forward(self, *xs):
        raise NotImplementedError

    def backward(self, *dys):
        raise NotImplementedError


class Dummy(Operation):
    """Placeholder creator for leaf tensors (reference: autograd.Dummy)."""

    def __init__(self, tensor, name=None):
        super().__init__(name)
        self.src = []
        self.y_id2idx = {id(tensor.data): 0}
        self.tensor = tensor
        self.requires_grad = tensor.requires_grad


def infer_dependency(op) -> dict:
    """Count, for each reachable op, how many downstream consumers must
    deliver a gradient before its own backward can run (reference:
    autograd.infer_dependency)."""
    counts = {op: 0}
    queue = deque([op])
    while queue:
        cur = queue.popleft()
        for src_op, _, _, _ in cur.src:
            if src_op is None:
                continue
            if src_op not in counts:
                counts[src_op] = 0
                queue.append(src_op)
            counts[src_op] += 1
    return counts


def gradients(y, dy=None):
    """Run backward and return {param_tensor: grad_tensor} (reference
    helper of the same name)."""
    return {p: g for p, g in backward(y, dy)}


def backward(y, dy=None):
    """Reverse-topo walk from loss ``y``; yields ``(tensor, grad)`` for
    every tensor with ``stores_grad`` as its gradient becomes final.

    Matches reference ``autograd.backward`` semantics including the
    generator contract consumed by ``opt.DistOpt`` (SURVEY.md §3.3).
    """
    assert isinstance(y, Tensor), "backward target must be a Tensor"
    if y.creator is None or not y.creator.requires_grad:
        return  # no grad flows anywhere (creator taped only for export)
    if dy is None:
        dy = jnp.ones(y.shape, dtype=y.data.dtype)
    else:
        dy = _raw(dy)

    dependency = infer_dependency(y.creator)
    ready = deque([(y.creator, (dy,))])
    not_ready = {}  # op -> list of accumulated output grads

    while ready:
        op, dys = ready.popleft()
        if isinstance(op, Dummy):
            continue
        dxs = op._do_backward(*dys)
        assert len(dxs) == len(op.src), (
            f"{op.name}: backward returned {len(dxs)} grads for "
            f"{len(op.src)} inputs"
        )
        for (src_op, x_id, x_tensor, x_stores_grad), dx in zip(op.src, dxs):
            if src_op is None or dx is None or _is_float0(dx):
                continue
            y_idx = src_op.y_id2idx[x_id]
            if src_op not in not_ready:
                slots = [None] * len(src_op.y_id2idx)
                slots[y_idx] = dx
                not_ready[src_op] = slots
            else:
                slots = not_ready[src_op]
                slots[y_idx] = dx if slots[y_idx] is None else slots[y_idx] + dx
            dependency[src_op] -= 1
            if dependency[src_op] == 0:
                if x_stores_grad and x_tensor is not None:
                    g = not_ready[src_op][y_idx]
                    yield (x_tensor, _wrap(g, x_tensor.device))
                if not isinstance(src_op, Dummy) and src_op.requires_grad:
                    ready.append((src_op, tuple(not_ready[src_op])))
                del not_ready[src_op]


def _is_float0(dx):
    return hasattr(dx, "dtype") and dx.dtype == jax.dtypes.float0


# ---------------------------------------------------------------------------
# Generic op machinery: forward = pure function, backward = jax.vjp.
# ---------------------------------------------------------------------------

class _Func(Operation):
    """Op whose VJP comes from jax.vjp of its pure forward function.

    ``fn(*xs)`` must be pure over its array arguments; keyword parameters
    are closed over at construction.  Replaces the reference's per-op
    hand-written backward + cuDNN bwd-kernel calls.
    """

    fn = None  # subclasses set a staticmethod, or pass fn to __init__

    def __init__(self, fn=None, name=None, **params):
        super().__init__(name)
        if fn is not None:
            self.fn = fn
        self.params = params

    def forward(self, *xs):
        f = self.fn
        if self.params:
            p = self.params
            g = lambda *a: f(*a, **p)  # noqa: E731
        else:
            g = f
        # vjp residuals pin input activations in device memory, so only
        # pay for them when some input actually requires grad (the tape
        # still records the op for export; backward() never descends
        # into requires_grad=False ops).
        if training and self.requires_grad:
            y, self.grad_fn = jax.vjp(g, *xs)
            # remember multi-output avals so unconsumed outputs can get
            # zero cotangents in backward
            self._out_aval = (
                [(o.shape, o.dtype) for o in y] if isinstance(y, tuple) else None
            )
            return y
        return g(*xs)

    def backward(self, *dys):
        if self._out_aval is not None:
            cts = tuple(
                d if d is not None else jnp.zeros(s, dt)
                for d, (s, dt) in zip(dys, self._out_aval)
            )
            return self.grad_fn(cts)
        return self.grad_fn(dys[0])


def _op(fn, *xs, _name=None, **params):
    """Apply a pure function as a recorded autograd op over Tensors."""
    return _Func(fn=fn, name=_name, **params)(*xs)


def checkpoint_op(fn, *xs, _name=None, **params):
    """Like ``_op`` but rematerialized: ``jax.checkpoint`` makes the VJP
    recompute the op's internals in backward instead of storing its
    residuals — HBM traded for FLOPs (the lever the reference lacks;
    its graph scheduler can only reorder, not recompute).  Apply to
    big fused bodies (attention, MoE dispatch, whole pipeline stages)
    where residuals dominate activation memory."""
    if params:
        wrapped = jax.checkpoint(lambda *a: fn(*a, **params))
    else:
        wrapped = jax.checkpoint(fn)
    op = _Func(fn=wrapped, name=_name)
    y = op(*xs)
    # keep the kwargs visible on the op instance for sonnx export
    # (already pre-bound into the checkpointed fn, so not re-passed)
    op.params = dict(params)
    return y


# ---------------------------------------------------------------------------
# Functional API (mirrors reference autograd module functions)
# ---------------------------------------------------------------------------

def relu(x):
    return _op(jax.nn.relu, x, _name="ReLU")


def leakyrelu(x, a=0.01):
    return _op(lambda v, a: jax.nn.leaky_relu(v, a), x, _name="LeakyRelu", a=a)


def elu(x, alpha=1.0):
    return _op(lambda v, alpha: jax.nn.elu(v, alpha), x, _name="Elu", alpha=alpha)


def selu(x):
    return _op(jax.nn.selu, x, _name="SeLU")


def gelu(x, approximate=True):
    return _op(lambda v, approximate: jax.nn.gelu(v, approximate=approximate),
               x, _name="Gelu", approximate=approximate)


def repeat_kv(x, repeats):
    """GQA K/V head broadcast: repeat (B, H_kv, S, D) heads ``repeats``×
    along axis 1 (element-interleaved, so K/V head i serves query heads
    [i·repeats, (i+1)·repeats)).  The op name and ``repeats`` param are
    the ONNX export contract (sonnx._dec_repeat_kv decomposes it to
    Reshape/Tile/Reshape) — both MHA variants must route through here."""
    return _op(lambda a, repeats: jnp.repeat(a, repeats, axis=1),
               x, _name="RepeatKV", repeats=repeats)


def sigmoid(x):
    return _op(jax.nn.sigmoid, x, _name="Sigmoid")


def tanh(x):
    return _op(jnp.tanh, x, _name="Tanh")


def softplus(x):
    return _op(jax.nn.softplus, x, _name="SoftPlus")


def softsign(x):
    return _op(lambda v: v / (1 + jnp.abs(v)), x, _name="SoftSign")


def relu6(x):
    return _op(jax.nn.relu6, x, _name="ReLU6")


def swish(x):
    return _op(jax.nn.swish, x, _name="Swish")


def hardsigmoid(x, alpha=0.2, gamma=0.5):
    return _op(lambda v, alpha, gamma: jnp.clip(alpha * v + gamma, 0, 1),
               x, _name="HardSigmoid", alpha=alpha, gamma=gamma)


def abs(x):  # noqa: A001
    return _op(jnp.abs, x, _name="Abs")


def exp(x):
    return _op(jnp.exp, x, _name="Exp")


def log(x):
    return _op(jnp.log, x, _name="Log")


def sqrt(x):
    return _op(jnp.sqrt, x, _name="Sqrt")


def square(x):
    return _op(jnp.square, x, _name="Square")


def sign(x):
    return _op(jnp.sign, x, _name="Sign")


def sin(x):
    return _op(jnp.sin, x, _name="Sin")


def cos(x):
    return _op(jnp.cos, x, _name="Cos")


def negative(x):
    return _op(jnp.negative, x, _name="Negative")


def reciprocal(x):
    return _op(jnp.reciprocal, x, _name="Reciprocal")


def clip(x, min=None, max=None):  # noqa: A002
    return _op(lambda v, min, max: jnp.clip(v, min, max), x,
               _name="Clip", min=min, max=max)


def add(a, b):
    return _op(jnp.add, a, b, _name="Add")


def sub(a, b):
    return _op(jnp.subtract, a, b, _name="Sub")


def mul(a, b):
    return _op(jnp.multiply, a, b, _name="Mul")


def div(a, b):
    return _op(jnp.divide, a, b, _name="Div")


def pow(a, b):  # noqa: A001
    return _op(jnp.power, a, b, _name="Pow")


def mul_scalar(a, s):
    """a * python-scalar s (reference: autograd.mul with a scalar arg —
    the scalar rides op.params, not the tape, so sonnx can export it)."""
    return _op(lambda v, s: v * s, a, _name="MulScalar", s=float(s))


def minimum(a, b):
    return _op(jnp.minimum, a, b, _name="Min")


def maximum(a, b):
    return _op(jnp.maximum, a, b, _name="Max")


def matmul(a, b):
    """Reference: autograd.Matmul → cuBLAS GEMM; here lax dot on the MXU
    (bf16 inputs under the amp policy)."""
    return _op(lambda u, v: jnp.matmul(*amp.cast_in(u, v)), a, b,
               _name="Matmul")


def add_bias(x, b, axis=0):
    """Reference: autograd.AddBias (bias add over rows/cols of a matrix).
    The bias follows x's dtype so bf16 activations stay bf16 under amp."""
    if axis == 0:
        return _op(lambda v, w: v + w.astype(v.dtype), x, b, _name="AddBias")
    return _op(lambda v, w: v + w.astype(v.dtype)[:, None], x, b,
               _name="AddBias")


def gemm(A, B, C=None, alpha=1.0, beta=1.0, transA=False, transB=False):
    """ONNX-style Gemm (reference autograd.Gemm)."""

    def f(a, b, *rest, alpha=alpha, beta=beta, transA=transA, transB=transB):
        a, b = amp.cast_in(a, b)
        a = a.T if transA else a
        b = b.T if transB else b
        y = alpha * jnp.matmul(a, b)
        if rest:
            y = y + beta * amp.cast_in(rest[0])
        return y

    if C is None:
        return _op(f, A, B, _name="Gemm")
    return _op(f, A, B, C, _name="Gemm")


def reshape(x, shape):
    return _op(lambda v, shape: jnp.reshape(v, shape), x,
               _name="Reshape", shape=tuple(int(s) for s in shape))


def flatten(x, axis=1):
    """Reference autograd.Flatten: collapse dims from ``axis`` on."""

    def f(v, axis):
        lead = int(np.prod(v.shape[:axis])) if axis > 0 else 1
        return jnp.reshape(v, (lead, -1))

    return _op(f, x, _name="Flatten", axis=axis)


def transpose(x, shape=None):
    """Reference autograd.Transpose(perm); arg named `shape` upstream."""
    perm = tuple(shape) if shape is not None else None
    return _op(lambda v, perm: jnp.transpose(v, perm), x,
               _name="Transpose", perm=perm)


def cat(xs, axis=0):
    # axis rides op.params so sonnx export can write the (required)
    # ONNX Concat axis attribute
    return _Func(
        fn=lambda *vs, axis=axis: jnp.concatenate(vs, axis=axis),
        name="Concat", axis=axis
    )(*xs)


concat = cat


def split(x, axis, parts):
    """Reference autograd.Split: sizes list → tuple of outputs."""
    offsets = np.cumsum(parts)[:-1].tolist()
    return _Func(
        fn=lambda v: tuple(jnp.split(v, offsets, axis=axis)), name="Split"
    )(x)


def squeeze(x, axis=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return _op(lambda v, ax: jnp.squeeze(v, ax), x, _name="Squeeze", ax=ax)


def unsqueeze(x, axis):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return _op(lambda v, ax: jnp.expand_dims(v, ax), x, _name="Unsqueeze", ax=ax)


def gather(x, axis, indices):
    idx = jnp.asarray(np.asarray(indices, dtype=np.int32))
    return _op(lambda v, axis, idx: jnp.take(v, idx, axis=axis), x,
               _name="Gather", axis=axis, idx=idx)


def mean(*xs):
    """Reference autograd.Mean: elementwise mean of N tensors."""
    return _Func(
        fn=lambda *vs: _sum_list(vs) / float(len(vs)), name="Mean"
    )(*xs)


def reduce_mean(x, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    return _op(lambda v, ax, keepdims: jnp.mean(v, axis=ax, keepdims=keepdims),
               x, _name="ReduceMean", ax=ax, keepdims=bool(keepdims))


def reduce_sum(x, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    return _op(lambda v, ax, keepdims: jnp.sum(v, axis=ax, keepdims=keepdims),
               x, _name="ReduceSum", ax=ax, keepdims=bool(keepdims))


def sum(*xs):  # noqa: A001  (reference: autograd.sum = eltwise sum of N)
    return _Func(fn=lambda *vs: _sum_list(vs), name="Sum")(*xs)


def _sum_list(vs):
    out = vs[0]
    for v in vs[1:]:
        out = out + v
    return out


def softmax(x, axis=1):
    """Reference autograd.SoftMax defaults to axis=1 (2-D logits)."""
    return _op(lambda v, axis: jax.nn.softmax(v, axis=axis), x,
               _name="SoftMax", axis=axis)


def log_softmax(x, axis=1):
    return _op(lambda v, axis: jax.nn.log_softmax(v, axis=axis), x,
               _name="LogSoftMax", axis=axis)


class _CrossEntropy(Operation):
    """Reference autograd.CrossEntropy: input is a probability matrix
    (post-softmax); target is one-hot or class indices."""

    def forward(self, p, t):
        t1h = _to_one_hot(t, p.shape)
        self._saved = (p, t1h)
        eps = 1e-10
        return -jnp.sum(t1h * jnp.log(p + eps)) / p.shape[0]

    def backward(self, dy):
        p, t1h = self._saved
        return (dy * (-t1h / (p + 1e-10)) / p.shape[0], None)


class _SoftMaxCrossEntropy(Operation):
    """Reference autograd.SoftMaxCrossEntropy: fused, numerically stable.
    Loss = mean over batch of CE(softmax(logits), target)."""

    def forward(self, x, t):
        # log-sum-exp in fp32 regardless of the amp compute dtype
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        t1h = _to_one_hot(t, x.shape)
        self._saved = (jnp.exp(logp), t1h, x.dtype)
        return -jnp.sum(t1h * logp) / x.shape[0]

    def backward(self, dy):
        p, t1h, xdt = self._saved
        dx = dy * (p - t1h) / p.shape[0]
        # cotangent must carry the logits' dtype so upstream vjps match
        return (dx.astype(xdt), None)


def _to_one_hot(t, logits_shape):
    if t.ndim == len(logits_shape) and t.shape == tuple(logits_shape):
        return t.astype(jnp.float32)
    return jax.nn.one_hot(t.astype(jnp.int32), logits_shape[-1], dtype=jnp.float32)


def cross_entropy(p, t):
    return _CrossEntropy()(p, t)


def softmax_cross_entropy(x, t):
    return _SoftMaxCrossEntropy()(x, t)


def mse_loss(x, t):
    return _op(lambda a, b: jnp.mean(jnp.square(a - b)), x, t, _name="MSE")


def binary_cross_entropy(p, t):
    eps = 1e-7
    return _op(
        lambda a, b: -jnp.mean(b * jnp.log(a + eps) + (1 - b) * jnp.log(1 - a + eps)),
        p, t, _name="BCE",
    )


def nll_loss(logp, t):
    def f(lp, tt):
        t1h = _to_one_hot(tt, lp.shape)
        return -jnp.sum(t1h * lp) / lp.shape[0]

    return _op(f, logp, t, _name="NLL")


class _Dropout(Operation):
    """Reference autograd.Dropout: scaled mask at train time.  The mask key
    comes from the input tensor's device PRNG so graph mode can thread it
    as traced state."""

    def __init__(self, ratio=0.5):
        super().__init__()
        self.ratio = float(ratio)

    def _do_forward(self, *xs):
        self._key = xs[0].device.rng_key()
        return super()._do_forward(*xs)

    def forward(self, x):
        self._mask = None
        if not training or self.ratio == 0.0:
            return x
        keep = 1.0 - self.ratio
        mask = jax.random.bernoulli(self._key, keep, x.shape)
        self._mask = mask
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def backward(self, dy):
        if self._mask is None:  # ratio == 0: identity
            return dy
        keep = 1.0 - self.ratio
        return jnp.where(self._mask, dy / keep, 0.0).astype(dy.dtype)


def dropout(x, ratio=0.5):
    return _Dropout(ratio)(x)


def identity(x):
    return _op(lambda v: v, x, _name="Identity")


def erf(x):
    return _op(jax.lax.erf, x, _name="Erf")


def cast(x, to):
    dt = to
    return _op(lambda v, dt: v.astype(dt), x, _name="Cast", dt=dt)


def equal(a, b):
    return _op(lambda x, y: (x == y).astype(jnp.float32), a, b, _name="Equal")


def greater(a, b):
    return _op(lambda x, y: (x > y).astype(jnp.float32), a, b, _name="Greater")


def less(a, b):
    return _op(lambda x, y: (x < y).astype(jnp.float32), a, b, _name="Less")


def where_op(cond, a, b):
    return _op(lambda c, x, y: jnp.where(c != 0, x, y), cond, a, b, _name="Where")


def layer_norm(x, scale, bias, axis=-1, eps=1e-12):
    """LayerNormalization (BERT uses eps=1e-12).  axis/eps ride op.params
    so sonnx export can emit them as node attributes."""

    def f(xv, sv, bv, axis, eps):
        # statistics in fp32 (bf16 variance is too coarse under amp)
        xf = xv.astype(jnp.float32)
        m = jnp.mean(xf, axis=axis, keepdims=True)
        v = jnp.var(xf, axis=axis, keepdims=True)
        y = (xf - m) * jax.lax.rsqrt(v + eps) * sv + bv
        return y.astype(xv.dtype)

    return _op(f, x, scale, bias, _name="LayerNorm", axis=axis, eps=eps)


def embedding(ids, W):
    """Row gather: ids (int tensor) indexes W (vocab, dim); W's grad is a
    scatter-add (XLA handles via the take VJP)."""
    return _op(lambda i, w: jnp.take(w, i.astype(jnp.int32), axis=0),
               ids, W, _name="Embedding")
