"""Parameter initializers (reference: python/singa/initializer.py,
unverified — gaussian/uniform/xavier/he fills mutating a Tensor)."""

import numpy as np

from .tensor import Tensor


def _fan(t: Tensor):
    shape = t.shape
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    return fan_in, fan_out


def uniform(t: Tensor, low=0.0, high=1.0):
    return t.uniform(low, high)


def gaussian(t: Tensor, mean=0.0, std=0.01):
    return t.gaussian(mean, std)


def xavier(t: Tensor):
    """Glorot uniform."""
    fan_in, fan_out = _fan(t)
    a = np.sqrt(6.0 / (fan_in + fan_out))
    return t.uniform(-a, a)


glorot_uniform = xavier


def glorot_normal(t: Tensor):
    fan_in, fan_out = _fan(t)
    std = np.sqrt(2.0 / (fan_in + fan_out))
    return t.gaussian(0.0, std)


def msra(t: Tensor):
    """He normal (reference name: msra)."""
    fan_in, _ = _fan(t)
    return t.gaussian(0.0, np.sqrt(2.0 / fan_in))


he_normal = msra


def he_uniform(t: Tensor):
    fan_in, _ = _fan(t)
    a = np.sqrt(6.0 / fan_in)
    return t.uniform(-a, a)


def lecun_uniform(t: Tensor):
    fan_in, _ = _fan(t)
    a = np.sqrt(3.0 / fan_in)
    return t.uniform(-a, a)


def constant(t: Tensor, value=0.0):
    return t.set_value(value)


def zeros(t: Tensor):
    return t.set_value(0.0)


def ones(t: Tensor):
    return t.set_value(1.0)
