"""VGG family (reference: examples/onnx/vgg16.py and
examples/onnx/vgg19.py import the ONNX model-zoo VGG checkpoints,
unverified — here the architecture is a native zoo model; deferred
Linear in_features lets the same net run at 224² ImageNet shapes or
32² CIFAR shapes without a config change).

Offline note: pretrained weights are unreachable (no network);
examples/onnx/zoo.py exercises the sonnx export→import round trip a
real checkpoint would take.
"""

from .. import layer
from .common import Classifier

_CFGS = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "vgg13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"],
    "vgg19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Classifier):
    def __init__(self, cfg, num_classes=1000, num_channels=3,
                 batch_norm=False, dropout=0.5, hidden=4096):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        features = []
        for v in cfg:
            if v == "M":
                features.append(layer.MaxPool2d(2, stride=2))
            else:
                features.append(layer.Conv2d(v, 3, padding=1,
                                             bias=not batch_norm))
                if batch_norm:
                    features.append(layer.BatchNorm2d())
                features.append(layer.ReLU())
        self.features = features  # list attrs discovered by _sublayers
        self.flatten = layer.Flatten()
        self.fc1 = layer.Linear(hidden)
        self.relu1 = layer.ReLU()
        self.drop1 = layer.Dropout(dropout)
        self.fc2 = layer.Linear(hidden)
        self.relu2 = layer.ReLU()
        self.drop2 = layer.Dropout(dropout)
        self.fc3 = layer.Linear(num_classes)

    def forward(self, x):
        y = x
        for f in self.features:
            y = f(y)
        y = self.flatten(y)
        y = self.drop1(self.relu1(self.fc1(y)))
        y = self.drop2(self.relu2(self.fc2(y)))
        return self.fc3(y)


def _make(name):
    def factory(batch_norm=False, **kw):
        return VGG(_CFGS[name], batch_norm=batch_norm, **kw)
    factory.__name__ = name
    return factory


vgg11 = _make("vgg11")
vgg13 = _make("vgg13")
vgg16 = _make("vgg16")
vgg19 = _make("vgg19")

_FACTORY = {"vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16,
            "vgg19": vgg19}


def create_model(name="vgg16", **kw):
    return _FACTORY[name](**kw)
