"""MobileNetV2 (reference: examples/onnx/mobilenet.py imports the ONNX
model-zoo MobileNetV2, unverified — here the architecture is a native
model, TPU-first: depthwise convs lower to
``lax.conv_general_dilated(feature_group_count=C)``, ReLU6 fuses into
the conv epilogue under XLA, and the whole net trains under the jitted
graph mode like every other zoo model).

Offline note: no pretrained weights are reachable from this container
(no network); examples/onnx/zoo.py round-trips this model through
sonnx export→import instead, which is the same code path a real
model-zoo checkpoint would exercise.
"""

from .. import layer
from .common import Classifier


class ConvBNReLU(layer.Layer):
    def __init__(self, out_channels, kernel_size=3, stride=1, group=1):
        super().__init__()
        padding = (kernel_size - 1) // 2
        self.conv = layer.Conv2d(out_channels, kernel_size, stride=stride,
                                 padding=padding, group=group, bias=False)
        self.bn = layer.BatchNorm2d()
        self.relu = layer.ReLU6()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InvertedResidual(layer.Layer):
    """MobileNetV2 block: 1×1 expand → 3×3 depthwise → 1×1 project,
    residual add when stride == 1 and channels match."""

    def __init__(self, in_channels, out_channels, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_channels * expand_ratio))
        self.use_res = stride == 1 and in_channels == out_channels
        blocks = []
        if expand_ratio != 1:
            blocks.append(ConvBNReLU(hidden, kernel_size=1))
        blocks.append(ConvBNReLU(hidden, kernel_size=3, stride=stride,
                                 group=hidden))  # depthwise
        self.blocks = blocks  # list attrs are discovered by _sublayers
        self.project = layer.Conv2d(out_channels, 1, bias=False)
        self.project_bn = layer.BatchNorm2d()
        self.add = layer.Add()

    def forward(self, x):
        y = x
        for b in self.blocks:
            y = b(y)
        y = self.project_bn(self.project(y))
        if self.use_res:
            y = self.add(y, x)
        return y


# (expand_ratio t, out_channels c, repeats n, first stride s)
_V2_CFG = [
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
]


class MobileNetV2(Classifier):
    def __init__(self, num_classes=1000, num_channels=3, width_mult=1.0,
                 dropout=0.2):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224

        def c(ch):
            # torchvision _make_divisible: round to the nearest multiple
            # of 8, never dropping more than 10% (the +8 correction)
            v = ch * width_mult
            new_v = max(8, int(v + 4) // 8 * 8)
            if new_v < 0.9 * v:
                new_v += 8
            return new_v

        self.stem = ConvBNReLU(c(32), kernel_size=3, stride=2)
        features = []
        in_ch = c(32)
        for t, ch, n, s in _V2_CFG:
            for i in range(n):
                features.append(InvertedResidual(
                    in_ch, c(ch), s if i == 0 else 1, t))
                in_ch = c(ch)
        self.features = features
        self.head = ConvBNReLU(c(1280) if width_mult > 1.0 else 1280,
                               kernel_size=1)
        self.pool = layer.GlobalAvgPool2d()
        self.dropout = layer.Dropout(dropout)
        self.fc = layer.Linear(num_classes)

    def forward(self, x):
        y = self.stem(x)
        for b in self.features:
            y = b(y)
        y = self.pool(self.head(y))
        return self.fc(self.dropout(y))


def mobilenet_v2(**kw):
    return MobileNetV2(**kw)


_FACTORY = {"mobilenet_v2": mobilenet_v2}


def create_model(name="mobilenet_v2", **kw):
    return _FACTORY[name](**kw)
