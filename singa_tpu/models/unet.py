"""U-Net (beyond reference parity — the reference zoo has no
segmentation family; this one exists to exercise the transposed-conv
decoder path natively: ``layer.ConvTranspose2d`` upsampling lowers to a
single ``lax.conv_general_dilated`` with lhs_dilation on the MXU, and
the skip concats ride XLA's fusion like any other elementwise chain).

Standard U-Net topology (Ronneberger et al., parameterized down for the
zoo): double-conv encoder blocks with 2×2 max-pool downsampling, a
bottleneck, and a decoder of 2×2-stride transposed convs + skip
concatenation, closed by a 1×1 conv to per-pixel class logits.  Trains
per-pixel softmax cross-entropy through the shared Classifier
scaffolding (labels (B, H, W) int).

Offline note: no pretrained weights are reachable from this container;
examples/onnx/zoo.py round-trips the model through sonnx export→import
instead (the ConvTranspose nodes exercise the round-4 importer).
"""

from .. import autograd, layer
from .common import Classifier, apply_dist_option


class DoubleConv(layer.Layer):
    def __init__(self, out_channels):
        super().__init__()
        self.conv1 = layer.Conv2d(out_channels, 3, padding=1, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.conv2 = layer.Conv2d(out_channels, 3, padding=1, bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu = layer.ReLU()

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        return self.relu(self.bn2(self.conv2(x)))


class Down(layer.Layer):
    def __init__(self, out_channels):
        super().__init__()
        self.pool = layer.MaxPool2d(2, 2)
        self.conv = DoubleConv(out_channels)

    def forward(self, x):
        return self.conv(self.pool(x))


class Up(layer.Layer):
    """2×2-stride transposed-conv upsample, concat the skip, double
    conv."""

    def __init__(self, out_channels):
        super().__init__()
        self.up = layer.ConvTranspose2d(out_channels, 2, stride=2)
        self.conv = DoubleConv(out_channels)

    def forward(self, x, skip):
        x = self.up(x)
        return self.conv(autograd.cat([skip, x], axis=1))


class UNet(Classifier):
    """num_classes per-pixel logits; base_channels scales the width
    (the canonical net is base 64 / depth 4 — the zoo default is
    smaller so the round-trip test stays fast)."""

    def __init__(self, num_classes=2, base_channels=16, depth=3):
        super().__init__()
        assert depth >= 1
        self.inc = DoubleConv(base_channels)
        self.downs = [Down(base_channels * 2 ** (i + 1))
                      for i in range(depth)]
        self.ups = [Up(base_channels * 2 ** (depth - 1 - i))
                    for i in range(depth)]
        self.outc = layer.Conv2d(num_classes, 1)

    def forward(self, x):
        h, w = x.shape[2], x.shape[3]
        f = 2 ** len(self.downs)
        if h % f or w % f:
            raise ValueError(
                f"UNet(depth={len(self.downs)}) needs H and W divisible "
                f"by {f}, got {h}x{w} — pooling floors odd sizes, so "
                "the decoder's skip concat would mismatch; pad/crop the "
                "input or lower depth")
        feats = [self.inc(x)]
        for d in self.downs:
            feats.append(d(feats[-1]))
        y = feats[-1]
        for u, skip in zip(self.ups, reversed(feats[:-1])):
            y = u(y, skip)
        return self.outc(y)

    def train_one_batch(self, x, y, dist_option="plain", spars=None):
        """y: (B, H, W) int labels — per-pixel cross-entropy."""
        out = self.forward(x)
        b, c, h, w = out.shape
        flat = autograd.reshape(
            autograd.transpose(out, (0, 2, 3, 1)), (b * h * w, c))
        loss = self.softmax_cross_entropy(
            flat, autograd.reshape(y, (b * h * w,)))
        apply_dist_option(self.optimizer, loss, dist_option, spars)
        return out, loss


def unet(num_classes=2, base_channels=16, depth=3):
    return UNet(num_classes, base_channels, depth)
