"""Shared classifier scaffolding for the model zoo.

The reference's example models (examples/cnn/model/*.py, unverified) each
repeat the same ``train_one_batch`` with a dist_option switch; this base
centralizes it."""

from .. import autograd, layer, model


def apply_dist_option(optimizer, loss, dist_option="plain", spars=None):
    """The reference's five-way dist_option switch, shared by every
    example model's train_one_batch."""
    if dist_option == "plain":
        optimizer(loss)
    elif dist_option == "fp16":
        optimizer.backward_and_update_half(loss)
    elif dist_option == "partialUpdate":
        optimizer.backward_and_partial_update(loss)
    elif dist_option == "sparseTopK":
        optimizer.backward_and_sparse_update(loss, topK=True, spars=spars)
    elif dist_option == "sparseThreshold":
        optimizer.backward_and_sparse_update(loss, topK=False, spars=spars)
    else:
        raise ValueError(f"unknown dist_option {dist_option!r}")


class Classifier(model.Model):
    """Model with softmax-cross-entropy training and the reference's
    five dist_option sync modes."""

    def __init__(self):
        super().__init__()
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def loss(self, out, ty):
        return self.softmax_cross_entropy(out, ty)

    def train_one_batch(self, x, y, dist_option="plain", spars=None):
        out = self.forward(x)
        loss = self.loss(out, y)
        apply_dist_option(self.optimizer, loss, dist_option, spars)
        return out, loss
