"""MLP (reference: examples/mlp/model.py, unverified — config #1 workload
in BASELINE.json)."""

from .. import layer, model


class MLP(model.Model):
    def __init__(self, data_size=10, perceptron_size=100, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.dimension = 2
        self.linear1 = layer.Linear(perceptron_size)
        self.relu1 = layer.ReLU()
        self.linear2 = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, inputs):
        y = self.linear1(inputs)
        y = self.relu1(y)
        y = self.linear2(y)
        return y

    def train_one_batch(self, x, y, dist_option="plain", spars=None):
        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        if dist_option == "plain":
            self.optimizer(loss)
        elif dist_option == "fp16":
            self.optimizer.backward_and_update_half(loss)
        elif dist_option == "partialUpdate":
            self.optimizer.backward_and_partial_update(loss)
        elif dist_option == "sparseTopK":
            self.optimizer.backward_and_sparse_update(loss, topK=True, spars=spars)
        elif dist_option == "sparseThreshold":
            self.optimizer.backward_and_sparse_update(loss, topK=False, spars=spars)
        return out, loss

    def set_optimizer(self, optimizer):
        super().set_optimizer(optimizer)


def create_model(**kwargs):
    return MLP(**kwargs)
