"""MLP (reference: examples/mlp/model.py, unverified — config #1 workload
in BASELINE.json)."""

from .. import layer, model


class MLP(model.Model):
    def __init__(self, data_size=10, perceptron_size=100, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.dimension = 2
        self.linear1 = layer.Linear(perceptron_size)
        self.relu1 = layer.ReLU()
        self.linear2 = layer.Linear(num_classes)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, inputs):
        y = self.linear1(inputs)
        y = self.relu1(y)
        y = self.linear2(y)
        return y

    def train_one_batch(self, x, y, dist_option="plain", spars=None):
        from .common import apply_dist_option

        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, y)
        apply_dist_option(self.optimizer, loss, dist_option, spars)
        return out, loss

    def set_optimizer(self, optimizer):
        super().set_optimizer(optimizer)


def create_model(**kwargs):
    return MLP(**kwargs)
