"""AlexNet (reference: examples/cnn/model/alexnet.py, unverified)."""

from .. import layer
from .common import Classifier


class AlexNet(Classifier):
    def __init__(self, num_classes=1000, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        self.conv1 = layer.Conv2d(64, 11, stride=4, padding=2)
        self.conv2 = layer.Conv2d(192, 5, padding=2)
        self.conv3 = layer.Conv2d(384, 3, padding=1)
        self.conv4 = layer.Conv2d(256, 3, padding=1)
        self.conv5 = layer.Conv2d(256, 3, padding=1)
        self.pool1 = layer.MaxPool2d(3, 2)
        self.pool2 = layer.MaxPool2d(3, 2)
        self.pool5 = layer.MaxPool2d(3, 2)
        self.relu1 = layer.ReLU()
        self.relu2 = layer.ReLU()
        self.relu3 = layer.ReLU()
        self.relu4 = layer.ReLU()
        self.relu5 = layer.ReLU()
        self.relu6 = layer.ReLU()
        self.relu7 = layer.ReLU()
        self.flatten = layer.Flatten()
        self.drop1 = layer.Dropout(0.5)
        self.drop2 = layer.Dropout(0.5)
        self.fc1 = layer.Linear(4096)
        self.fc2 = layer.Linear(4096)
        self.fc3 = layer.Linear(num_classes)

    def forward(self, x):
        y = self.pool1(self.relu1(self.conv1(x)))
        y = self.pool2(self.relu2(self.conv2(y)))
        y = self.relu3(self.conv3(y))
        y = self.relu4(self.conv4(y))
        y = self.pool5(self.relu5(self.conv5(y)))
        y = self.flatten(y)
        y = self.drop1(self.relu6(self.fc1(y)))
        y = self.drop2(self.relu7(self.fc2(y)))
        return self.fc3(y)


def create_model(**kw):
    return AlexNet(**kw)
