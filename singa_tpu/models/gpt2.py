"""GPT-2 — decoder-only causal LM.

Reference parity note: upstream SINGA ships GPT-2 only as an
ONNX-imported example (examples/onnx/gpt2.py, unverified — SURVEY.md
§2.4 lists the ONNX model zoo); like models/bert.py, this is the
TPU-native first-class implementation, and examples/onnx/gpt2.py
round-trips it through sonnx.

TPU-first design:
  * the whole decoder is one jitted graph-mode step (fused causal
    attention on the MXU);
  * fully parallel-aware: pass a ``ShardingPlan`` and the blocks become
    Megatron tensor-parallel (+ ring-attention sequence-parallel) via
    parallel/tensor_parallel.py; ``moe_every`` turns every Nth MLP into
    an expert-parallel GShard MoE (parallel/moe.py) — a GPT-MoE;
  * ``tie_weights=True`` (GPT-2 convention) reuses the token embedding
    as the LM head through a taped transpose-matmul.
"""

import numpy as np
import jax.numpy as jnp

from .. import autograd, layer, model, tensor
from ..tensor import Tensor


class GPT2Config:
    def __init__(self, vocab_size=50257, n_positions=1024, n_embd=768,
                 n_layer=12, n_head=12, n_inner=None, dropout=0.1,
                 layer_norm_eps=1e-5, tie_weights=True, moe_every=None,
                 moe_experts=8, moe_top_k=2, moe_aux_weight=0.01,
                 moe_capacity_factor=1.25, moe_groups=None, remat=False,
                 attn_impl="auto", n_kv_head=None, attn_window=None):
        self.vocab_size = vocab_size
        self.n_positions = n_positions
        self.n_embd = n_embd
        self.n_layer = n_layer
        self.n_head = n_head
        # grouped-query attention: n_kv_head < n_head shares each K/V
        # head across a group of n_head // n_kv_head query heads
        # (n_head/n_kv_head× smaller KV cache at decode)
        self.n_kv_head = int(n_kv_head or n_head)
        if n_head % self.n_kv_head != 0:
            raise ValueError(f"n_head {n_head} not divisible by "
                             f"n_kv_head {self.n_kv_head}")
        # sliding-window (Mistral-style) causal attention: each query
        # sees the previous attn_window positions only; the KV-cached
        # decoder keeps an O(attn_window) rolling cache
        self.attn_window = None if attn_window is None else int(attn_window)
        if self.attn_window is not None and self.attn_window < 1:
            raise ValueError(f"attn_window must be >= 1, "
                             f"got {attn_window}")
        self.n_inner = n_inner or 4 * n_embd
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.tie_weights = tie_weights
        # MoE: every Nth block's MLP becomes a MoEFFN (None = dense)
        self.moe_every = moe_every
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_aux_weight = moe_aux_weight
        self.moe_capacity_factor = moe_capacity_factor
        # routing-group override (default: plan's data-axis size); lets
        # a serial model reproduce a sharded run's grouped routing
        self.moe_groups = moe_groups
        # remat: recompute attention internals in backward
        # (jax.checkpoint) — memory for FLOPs on long sequences
        self.remat = remat
        # attn_impl: "fused" (S x S scores in HBM) or "flash" (Pallas
        # online-softmax fwd+bwd kernels, O(S·D) HBM).  "auto" picks by
        # the measured crossover, re-swept in round 4 (real v5e, GPT-2
        # small, 8192 tokens/step): flash TIES fused at S in {256, 512}
        # (104.4 vs 103.4 / 108.0 vs 108.0 k tok/s) and WINS 31% at
        # S=1024 (100.2 vs 76.5) — the threshold moved down from
        # round 3's 2048.  Flash stays the only impl surviving
        # S >= 16384 on one chip (LONGCTX.json); fused keeps short S.
        if attn_impl == "auto":
            attn_impl = "flash" if n_positions >= 1024 else "fused"
        self.attn_impl = attn_impl

    @classmethod
    def small(cls, **kw):
        """GPT-2 small (124M)."""
        return cls(**kw)

    @classmethod
    def medium(cls, **kw):
        kw.setdefault("n_embd", 1024)
        kw.setdefault("n_layer", 24)
        kw.setdefault("n_head", 16)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        """For tests: 2 layers, 64 hidden."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("n_positions", 128)
        kw.setdefault("n_embd", 64)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 4)
        kw.setdefault("n_inner", 128)
        return cls(**kw)


class GPT2Model(model.Model):
    """Decoder trunk: wte + wpe -> pre-LN causal blocks -> final LN."""

    def __init__(self, cfg=None, plan=None):
        super().__init__()
        from ..parallel.tensor_parallel import (
            ParallelTransformerBlock, VocabParallelEmbedding)

        self.cfg = cfg or GPT2Config.small()
        self.plan = plan
        c = self.cfg
        self.wte = VocabParallelEmbedding(c.vocab_size, c.n_embd, plan)
        self.wpe = layer.Embedding(c.n_positions, c.n_embd, std=0.01)
        self.blocks = []
        for i in range(c.n_layer):
            moe = (c.moe_every is not None
                   and (i + 1) % c.moe_every == 0)
            self.blocks.append(ParallelTransformerBlock(
                c.n_head, c.n_inner, plan, dropout=c.dropout, causal=True,
                eps=c.layer_norm_eps, num_kv_heads=c.n_kv_head,
                window=c.attn_window,
                moe_experts=c.moe_experts if moe else None,
                moe_top_k=c.moe_top_k,
                moe_capacity_factor=c.moe_capacity_factor,
                moe_groups=c.moe_groups,
                remat=c.remat, use_flash=c.attn_impl == "flash"))
        self.ln_f = layer.LayerNorm(c.layer_norm_eps)

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = tensor.from_numpy(
            np.broadcast_to(np.arange(s, dtype=np.int32), (b, s)).copy(),
            input_ids.device)
        x = autograd.add(self.wte(input_ids), self.wpe(pos))
        if self.cfg.dropout > 0:
            x = autograd.dropout(x, self.cfg.dropout)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)

    def aux_losses(self):
        """Taped MoE load-balance losses from the last forward."""
        return [blk.aux_loss for blk in self.blocks
                if blk.aux_loss is not None]


class GPT2LMHead(model.Model):
    """Causal-LM head; the training workload (next-token prediction)."""

    def __init__(self, cfg=None, plan=None):
        super().__init__()
        self.cfg = cfg or GPT2Config.small()
        self.plan = plan
        self.transformer = GPT2Model(self.cfg, plan)
        if not self.cfg.tie_weights:
            from ..parallel.tensor_parallel import ColumnParallelLinear

            self.lm_head = ColumnParallelLinear(
                self.cfg.vocab_size, plan, bias=False, gather_output=True)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, input_ids):
        h = self.transformer.forward(input_ids)
        if self.cfg.tie_weights:
            # logits = h @ wte^T (GPT-2 weight tying); with a plan the
            # vocab-sharded table makes this a column-parallel matmul
            wt = autograd.transpose(self.transformer.wte.W, (1, 0))
            logits = autograd.matmul(h, wt)
        else:
            logits = self.lm_head(h)
        return logits

    def train_one_batch(self, input_ids, labels):
        """labels: next-token ids, same shape as input_ids (callers pass
        ids shifted by one; positions to ignore use label -1 — their
        loss AND gradient are zero, and the mean is taken over valid
        (label >= 0) positions only, standard ignore_index semantics)."""
        logits = self.forward(input_ids)
        b, s, v = logits.shape
        loss = self.loss_fn(
            autograd.reshape(logits, (b * s, v)),
            autograd.reshape(labels, (b * s,)))
        # _SoftMaxCrossEntropy zeroes ignored rows (one_hot(-1) is all
        # zeros) but divides by ALL rows; rescale so the mean is over
        # valid positions, else reported loss (and effective lr) shrinks
        # with the ignore fraction
        scale = autograd._op(
            lambda lab: (b * s) / jnp.maximum(jnp.sum(
                (lab.reshape(-1) >= 0).astype(jnp.float32)), 1.0),
            labels, _name="IgnoreIndexScale")
        loss = autograd.mul(loss, scale)
        for aux in self.transformer.aux_losses():
            loss = autograd.add(
                loss, autograd.mul_scalar(aux, self.cfg.moe_aux_weight))
        self.optimizer(loss)
        return logits, loss

    # -- sampling (fixed-shape, jit-friendly: full-context forward per
    #    emitted token, like examples/rnn's fixed-shape sampling) --------
    def generate(self, prompt_ids, max_new_tokens=20, temperature=1.0,
                 rng=None, use_cache=None, top_k=0, top_p=None,
                 min_p=None, repetition_penalty=None):
        """Greedy/temperature sampling with optional top-k / top-p
        (nucleus) filtering. prompt_ids: np.ndarray (S0,).

        ``prompt_ids``: one 1-D prompt (returns a 1-D array), or —
        round 5, KV-cached path only — a list/2-D batch of prompts,
        possibly ragged (returns a list of 1-D arrays; rows decode
        lockstep in one executable via models/gpt2_decode.generate).

        ``use_cache`` (default auto): dense single-device models whose
        generation fits n_positions decode through the KV-cached
        incremental path (models/gpt2_decode.py — one compiled
        prefill + lax.scan, O(S·D) per token) instead of one
        full-context forward per token; plan-sharded models decode
        there too (SPMD over the mesh, round 4), and MoE models since
        round 5 (capacity-free expert routing — token-equal to the
        windowed path when its capacity drops nothing); over-length
        generations use the windowed path below."""
        from . import gpt2_decode as _gd

        # shared classification with gpt2_decode (KV-cached path only)
        if _gd._is_batch(prompt_ids):
            if use_cache is False:
                raise ValueError(
                    "batched generate requires the KV-cached path "
                    "(use_cache=False is single-prompt only); loop "
                    "over rows for the windowed sampler")
            rows = [np.asarray(r, np.int32).reshape(-1)
                    for r in list(prompt_ids)]
            over = any(len(r) + max_new_tokens > self.cfg.n_positions
                       for r in rows)
            if over and use_cache is not True:
                # a batch that exceeds n_positions cannot ride the KV
                # cache; loop EVERY row through the windowed fallback
                # (all rows on one path — mixing cached and windowed
                # rows would sample from different RNG streams), the
                # exact loop the old error message told the caller to
                # write (round-6 fix; use_cache=True keeps the
                # explicit-request ValueError below)
                return [self.generate(
                    r, max_new_tokens=max_new_tokens,
                    temperature=temperature, rng=rng, use_cache=False,
                    top_k=top_k, top_p=top_p, min_p=min_p,
                    repetition_penalty=repetition_penalty)
                    for r in rows]
            was_training = getattr(self, "training", False)
            self.eval()
            try:
                return _gd.generate(
                    self, prompt_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, rng=rng, top_k=top_k,
                    top_p=top_p, min_p=min_p,
                    repetition_penalty=repetition_penalty)
            finally:
                if was_training:
                    self.train(True)
        n0 = len(np.asarray(prompt_ids).reshape(-1))
        blocks = self.transformer.blocks
        initialized = bool(blocks) and blocks[0].mlp is not None
        if use_cache is None:
            # plan-sharded dense models decode through the KV cache too
            # since round 4 (extract_params lays weights out per the
            # plan; the pure-jnp generation jits SPMD over the mesh)
            use_cache = (initialized  # deferred init needs a forward
                         and n0 + max_new_tokens <= self.cfg.n_positions)
        # .training only exists after train()/eval(); an un-compiled
        # model can still generate (the windowed path lazily inits)
        # validate sampling params up front so BOTH paths (KV-cached and
        # windowed) fail the same way — the windowed math would otherwise
        # NaN on top_p=0 instead of raising
        if top_k and top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        # clamp like HF: top_k > vocab means no filter (the windowed
        # np.sort path would IndexError otherwise — advisor r04)
        top_k = min(int(top_k or 0), self.cfg.vocab_size)
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if min_p is not None and not 0.0 < min_p <= 1.0:
            raise ValueError(f"min_p must be in (0, 1], got {min_p}")
        if repetition_penalty is not None and repetition_penalty <= 0.0:
            raise ValueError(f"repetition_penalty must be > 0, "
                             f"got {repetition_penalty}")
        was_training = getattr(self, "training", False)
        self.eval()
        try:
            if use_cache:
                from . import gpt2_decode

                return gpt2_decode.generate(
                    self, prompt_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, rng=rng, top_k=top_k,
                    top_p=top_p, min_p=min_p,
                    repetition_penalty=repetition_penalty)
            ids = list(np.asarray(prompt_ids).tolist())
            ctx = self.cfg.n_positions
            wte = self.transformer.wte
            if hasattr(wte, "W"):
                dev = wte.W.device  # follow the params
            else:  # un-compiled model: first forward will deferred-init
                from .. import device as device_module

                dev = device_module.get_default_device()
            for _ in range(max_new_tokens):
                live = ids[-ctx:]
                # causal attention ignores positions to the RIGHT, so a
                # fixed-size right-padded window keeps the forward shape
                # static (one compile for the whole generation) and the
                # logits at index len(live)-1 are exact
                window = np.zeros((1, ctx), np.int32)
                window[0, :len(live)] = live
                x = tensor.from_numpy(window, dev)
                logits = self.forward(x)
                last = tensor.to_numpy(logits)[0, len(live) - 1]
                last = last.astype(np.float64)
                if repetition_penalty is not None \
                        and repetition_penalty != 1.0:
                    # CTRL/HF semantics: seen tokens (the WHOLE
                    # sequence so far, prompt included) are divided
                    # when positive, multiplied when negative —
                    # applied before greedy argmax too
                    seen = np.unique(np.asarray(ids, np.int64))
                    pen = np.where(last[seen] > 0,
                                   last[seen] / repetition_penalty,
                                   last[seen] * repetition_penalty)
                    last[seen] = pen
                if temperature <= 0:
                    nxt = int(np.argmax(last))
                else:
                    logit = last / temperature
                    if top_k:
                        kth = np.sort(logit)[-int(top_k)]
                        logit = np.where(logit < kth, -np.inf, logit)
                    if top_p is not None:
                        order = np.argsort(-logit)
                        sp = np.exp(logit[order] - logit[order][0])
                        sp /= sp.sum()
                        cum = np.cumsum(sp)
                        keep = np.zeros(len(logit), bool)
                        keep[order] = (cum - sp) < top_p
                        logit = np.where(keep, logit, -np.inf)
                    if min_p is not None:
                        # keep p >= min_p·p_max
                        logit = np.where(
                            logit < logit.max() + np.log(min_p),
                            -np.inf, logit)
                    p = np.exp(logit - logit.max())
                    p /= p.sum()
                    r = rng or np.random
                    nxt = int(r.choice(len(p), p=p))
                ids.append(nxt)
            return np.asarray(ids, np.int32)
        finally:
            if was_training:
                self.train(True)


    # -- serving (round 6): iteration-level continuous batching --------
    def serve(self, **kw):
        """An in-process continuous-batching inference engine over this
        model's KV-cached decoder (singa_tpu.serve.InferenceEngine):
        asynchronous request admission, a fixed-shape slot pool (no
        recompiles), per-step retirement and backfill.  Keyword args
        pass through to the engine (``max_slots``, ``max_len``,
        ``dtype``, ``top_k``, ``top_p``, ``scheduler``, ``clock``,
        ``slo`` — declarative latency targets, see
        ``singa_tpu.observe.SLO`` — ``prefix_cache`` — a
        ``serve.PrefixCacheConfig`` enabling block-granular radix
        prefix caching + pinned multi-turn sessions — and the
        fast-decode knobs: ``draft_model=`` + ``spec_k=`` for
        speculative decoding (up to spec_k tokens per step; greedy
        streams byte-identical to the plain engine, sampled traffic
        served via rejection sampling) and ``cache_dtype="int8"`` for
        a quantized KV arena.  ``paged=`` — a ``serve.PagedConfig``
        replacing the worst-case slot arena with ONE block-paged KV
        pool shared with the prefix cache: admission by blocks-free,
        block-by-block growth, priority preemption with byte-exact
        swap/resume; pair with ``scheduler="priority"`` for strict-
        priority admission).  ``tp=k`` — tensor-parallel serving
        (serve/tp.py): ONE engine's weights and KV arenas shard
        across a k-device mesh (Megatron column/row layout under
        shard_map, attention heads + MLP columns partitioned, one
        psum per attention output and per MLP fc2, each shard owning
        the (…, H_kv/k, …) slice of every cache pool) — the
        larger-than-one-device serving story, with token streams
        pinned identical to the single-device engine and every other
        knob composing unchanged.  Long-context serving (the
        long-context round): ``PagedConfig(prefill_token_budget=)``
        splits a long admission's prefill across steps in
        block-width chunks so decode lanes never stall behind it;
        sliding-window models (``GPT2Config(attn_window=)``) serve
        in paged mode holding O(window) blocks per slot; and
        ``TPConfig(ring_prefill=True)`` prefills cold long prompts
        sequence-sharded over the tp mesh.  ``ep=EPConfig(ep=, tp=)``
        — expert-parallel MoE serving (serve/ep.py): experts shard
        over an ``ep`` mesh axis with capacity-bounded GShard
        dispatch inside the jitted pool steps, dense layers keep the
        Megatron layout on an orthogonal ``tp`` axis, and streams
        stay token-identical to the single-device MoE engine.
        ``pp=PPConfig(stages=, microbatches=)`` — pipeline-parallel
        serving (serve/pp.py): the layer stack partitions into
        stages, each owning its layer slice of the paged KV pool,
        with microbatched decode so pipeline bubbles amortize across
        the continuous batch (requires ``paged=``).  See
        docs/SERVING.md "Fast decode", "Paged KV and preemption",
        "Tensor-parallel serving", "Long-context serving", and
        "Expert-parallel and pipeline serving"."""
        from ..serve import InferenceEngine

        return InferenceEngine(self, **kw)

    def serve_fleet(self, replicas=2, **kw):
        """N supervised engine replicas behind a health-checked router
        (singa_tpu.serve.ServeFleet): least-loaded / SLO-headroom
        scoring, sticky ``pin_session`` routing, cross-replica
        failover with never-started requeue parity, optional hedged
        re-dispatch.  Replicas share this model's weights and jitted
        executables but own their KV arena and prefix cache.  Keyword
        args: ``router``, ``restart_budget``, ``budget_reset_after_s``,
        ``shed_on_slo_pressure``, ``hedge_after_steps``, plus
        everything :meth:`serve` accepts (forwarded to every replica's
        engine).  ``tp=k`` builds a fleet of TENSOR-PARALLEL replicas:
        the device mesh partitions into ``replicas`` disjoint k-wide
        groups (tp inside each replica, data parallelism across them;
        ``tp x replicas`` must fit the mesh).  ``ep=``/``pp=`` do the
        same for expert-parallel MoE and pipeline-parallel replicas —
        (ep x tp)-wide or stage-wide disjoint groups respectively.
        See docs/SERVING.md "Fleet serving", "Tensor-parallel
        serving", and "Expert-parallel and pipeline serving"."""
        from ..serve import ServeFleet

        return ServeFleet(self, replicas=replicas, **kw)


def create_model(size="small", plan=None, **kw):
    cfg = getattr(GPT2Config, size)(**kw)
    return GPT2LMHead(cfg, plan)
