"""Simple CNN (reference: examples/cnn/model/cnn.py, unverified — the
LeNet-style conv/pool/fc net used for MNIST)."""

from .. import layer
from .common import Classifier


class CNN(Classifier):
    def __init__(self, num_classes=10, num_channels=1):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 28
        self.dimension = 4
        self.conv1 = layer.Conv2d(20, 5, padding=0, activation="RELU")
        self.conv2 = layer.Conv2d(50, 5, padding=0, activation="RELU")
        self.pooling1 = layer.MaxPool2d(2, 2, padding=0)
        self.pooling2 = layer.MaxPool2d(2, 2, padding=0)
        self.relu = layer.ReLU()
        self.linear1 = layer.Linear(500)
        self.linear2 = layer.Linear(num_classes)
        self.flatten = layer.Flatten()

    def forward(self, x):
        y = self.conv1(x)
        y = self.pooling1(y)
        y = self.conv2(y)
        y = self.pooling2(y)
        y = self.flatten(y)
        y = self.linear1(y)
        y = self.relu(y)
        y = self.linear2(y)
        return y


def create_model(**kwargs):
    return CNN(**kwargs)
