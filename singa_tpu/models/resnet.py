"""ResNet family (reference: examples/cnn/model/resnet.py, unverified —
torchvision-style BasicBlock/Bottleneck resnet18..152 for CIFAR/ImageNet;
config #2/#5 workloads in BASELINE.json)."""

from .. import layer
from .common import Classifier


class BasicBlock(layer.Layer):
    expansion = 1

    def __init__(self, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = layer.Conv2d(planes, 3, stride=stride, padding=1,
                                  bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.conv2 = layer.Conv2d(planes, 3, stride=1, padding=1, bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.relu2 = layer.ReLU()
        self.add = layer.Add()
        self.downsample = downsample

    def forward(self, x):
        residual = x
        out = self.relu1(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu2(self.add(out, residual))


class Bottleneck(layer.Layer):
    expansion = 4

    def __init__(self, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = layer.Conv2d(planes, 1, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.conv2 = layer.Conv2d(planes, 3, stride=stride, padding=1,
                                  bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.conv3 = layer.Conv2d(planes * self.expansion, 1, bias=False)
        self.bn3 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.relu2 = layer.ReLU()
        self.relu3 = layer.ReLU()
        self.add = layer.Add()
        self.downsample = downsample

    def forward(self, x):
        residual = x
        out = self.relu1(self.bn1(self.conv1(x)))
        out = self.relu2(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu3(self.add(out, residual))


class Downsample(layer.Layer):
    def __init__(self, planes, stride):
        super().__init__()
        self.conv = layer.Conv2d(planes, 1, stride=stride, bias=False)
        self.bn = layer.BatchNorm2d()

    def forward(self, x):
        return self.bn(self.conv(x))


class ResNet(Classifier):
    def __init__(self, block, layers, num_classes=1000, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 224
        self.dimension = 4
        self.conv1 = layer.Conv2d(64, 7, stride=2, padding=3, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.relu = layer.ReLU()
        self.maxpool = layer.MaxPool2d(kernel_size=3, stride=2, padding=1)
        self.inplanes = 64
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = layer.GlobalAvgPool2d()
        self.fc = layer.Linear(num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Downsample(planes * block.expansion, stride)
        blocks_list = [block(planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            blocks_list.append(block(planes))
        return blocks_list

    def forward(self, x):
        y = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for blk in self.layer1 + self.layer2 + self.layer3 + self.layer4:
            y = blk(y)
        y = self.avgpool(y)
        return self.fc(y)


def resnet18(**kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw):
    return ResNet(Bottleneck, [3, 4, 6, 3], **kw)


def resnet101(**kw):
    return ResNet(Bottleneck, [3, 4, 23, 3], **kw)


def resnet152(**kw):
    return ResNet(Bottleneck, [3, 8, 36, 3], **kw)


_FACTORY = {
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
}


def create_model(name="resnet50", **kw):
    return _FACTORY[name](**kw)
