"""char-RNN language model (reference: examples/rnn/ char-rnn LSTM,
unverified — config #3 workload in BASELINE.json): one-hot chars →
multi-layer LSTM → per-timestep linear over the vocab."""

import numpy as np

from .. import autograd, layer, model, tensor


class CharRNN(model.Model):
    def __init__(self, vocab_size, hidden_size=256, num_layers=2,
                 seq_length=100, cell="lstm"):
        super().__init__()
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.seq_length = seq_length
        # cell: any of the reference cuDNN RNN modes (ops/rnn.py) —
        # lstm / gru / vanilla_tanh / vanilla_relu
        cls = {"lstm": layer.LSTM, "gru": layer.GRU,
               "vanilla_tanh": lambda *a, **k: layer.RNN(
                   *a, nonlinearity="tanh", **k),
               "vanilla_relu": lambda *a, **k: layer.RNN(
                   *a, nonlinearity="relu", **k)}[cell]
        self.lstm = cls(hidden_size, num_layers=num_layers,
                        batch_first=True)
        self.dense = layer.Linear(vocab_size)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, x, hx=None, cx=None):
        """x: (B, T, vocab) one-hot. Returns (B*T, vocab) logits."""
        y, _ = self.lstm(x, hx, cx)
        y = autograd.reshape(y, (-1, self.hidden_size))
        return self.dense(y)

    def train_one_batch(self, x, y, dist_option="plain", spars=None):
        from .common import apply_dist_option

        out = self.forward(x)
        loss = self.softmax_cross_entropy(out, autograd.reshape(y, (-1,)))
        apply_dist_option(self.optimizer, loss, dist_option, spars)
        return out, loss


def one_hot(idx_batch, vocab_size):
    """(B, T) int -> (B, T, V) float32 one-hot."""
    b, t = idx_batch.shape
    out = np.zeros((b, t, vocab_size), np.float32)
    out[np.arange(b)[:, None], np.arange(t)[None, :], idx_batch] = 1.0
    return out


def create_model(**kw):
    return CharRNN(**kw)
