"""BERT (reference: examples/onnx/bert.py imports ONNX BERT-base through
sonnx, unverified — config #4 workload in BASELINE.json).

Two routes exist here:
  * this native implementation (TPU-first: fused attention on the MXU,
    whole encoder jitted in graph mode), matching BERT-base hyperparams
    (L=12, H=768, A=12, 110M params);
  * the sonnx import path (examples/onnx/bert.py) for ONNX checkpoints.
"""

import numpy as np

from .. import autograd, layer, model, tensor
from ..tensor import Tensor


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout=0.1, attn_dropout=0.1,
                 layer_norm_eps=1e-12, use_flash=False, remat=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attn_dropout = attn_dropout
        self.layer_norm_eps = layer_norm_eps
        self.use_flash = use_flash
        self.remat = remat  # jax.checkpoint'd attention backward

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        """For tests: 2 layers, 64 hidden."""
        kw.setdefault("vocab_size", 1000)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 128)
        return cls(**kw)


class BertEmbeddings(layer.Layer):
    def __init__(self, cfg, plan=None):
        super().__init__()
        if plan is not None:
            from ..parallel.tensor_parallel import VocabParallelEmbedding

            self.word = VocabParallelEmbedding(cfg.vocab_size,
                                               cfg.hidden_size, plan)
        else:
            self.word = layer.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position = layer.Embedding(cfg.max_position_embeddings,
                                        cfg.hidden_size)
        self.token_type = layer.Embedding(cfg.type_vocab_size,
                                          cfg.hidden_size)
        self.ln = layer.LayerNorm(cfg.layer_norm_eps)
        self.dropout = cfg.hidden_dropout

    def forward(self, input_ids, token_type_ids):
        b, s = input_ids.shape
        pos = tensor.from_numpy(
            np.broadcast_to(np.arange(s, dtype=np.int32), (b, s)).copy(),
            input_ids.device)
        e = autograd.add(
            autograd.add(self.word(input_ids), self.position(pos)),
            self.token_type(token_type_ids))
        e = self.ln(e)
        if self.dropout > 0:
            e = autograd.dropout(e, self.dropout)
        return e


class BertLayer(layer.Layer):
    """Post-LN encoder block.  With a ShardingPlan the projections are
    Megatron column/row-parallel and attention runs head-sharded (ring
    attention over `seq` when the mesh shards sequences) — the same
    state names either way, so checkpoints move between layouts."""

    def __init__(self, cfg, plan=None):
        super().__init__()
        if plan is not None:
            from ..parallel.tensor_parallel import (
                ColumnParallelLinear, ParallelMHA, RowParallelLinear)

            # use_flash + plan delegates to ParallelMHA's policy: with a
            # sharded seq axis each ring step runs the flash kernel
            # inside shard_map; without one it warns and uses the fused
            # head-sharded path (no GSPMD rule for bare pallas_call)
            self.attn = ParallelMHA(cfg.num_attention_heads, plan,
                                    dropout=cfg.attn_dropout,
                                    use_flash=cfg.use_flash,
                                    remat=cfg.remat)
            self.fc1 = ColumnParallelLinear(cfg.intermediate_size, plan)
            self.fc2 = RowParallelLinear(cfg.hidden_size, plan)
        else:
            from ..ops.attention import MultiHeadAttention

            self.attn = MultiHeadAttention(cfg.num_attention_heads,
                                           dropout=cfg.attn_dropout,
                                           use_flash=cfg.use_flash,
                                           remat=cfg.remat)
            self.fc1 = layer.Linear(cfg.intermediate_size)
            self.fc2 = layer.Linear(cfg.hidden_size)
        self.ln1 = layer.LayerNorm(cfg.layer_norm_eps)
        self.ln2 = layer.LayerNorm(cfg.layer_norm_eps)
        self.dropout = cfg.hidden_dropout

    def forward(self, x, mask=None):
        a = self.attn(x, mask)
        if self.dropout > 0:
            a = autograd.dropout(a, self.dropout)
        x = self.ln1(autograd.add(x, a))
        h = autograd.gelu(self.fc1(x))
        h = self.fc2(h)
        if self.dropout > 0:
            h = autograd.dropout(h, self.dropout)
        return self.ln2(autograd.add(x, h))


class BertEncoder(layer.Layer):
    def __init__(self, cfg, plan=None):
        super().__init__()
        self.layers = [BertLayer(cfg, plan)
                       for _ in range(cfg.num_hidden_layers)]

    def forward(self, x, mask=None):
        for lyr in self.layers:
            x = lyr(x, mask)
        return x


class BertModel(model.Model):
    """Encoder trunk; forward returns (sequence_output, pooled_output)."""

    def __init__(self, cfg=None, plan=None):
        super().__init__()
        self.cfg = cfg or BertConfig.base()
        self.embeddings = BertEmbeddings(self.cfg, plan)
        self.encoder = BertEncoder(self.cfg, plan)
        self.pooler = layer.Linear(self.cfg.hidden_size)

    def _attn_mask(self, attention_mask):
        """(B, S) 1/0 mask -> (B, 1, 1, S) additive -1e9 mask Tensor."""
        if attention_mask is None:
            return None
        m = attention_mask
        return _mask_op(m)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        if token_type_ids is None:
            token_type_ids = tensor.from_numpy(
                np.zeros(input_ids.shape, np.int32), input_ids.device)
        x = self.embeddings(input_ids, token_type_ids)
        x = self.encoder(x, self._attn_mask(attention_mask))
        pooled = autograd.tanh(self.pooler(_first_token(x)))
        return x, pooled


def _mask_op(m):
    return autograd._op(
        lambda mv: (1.0 - mv.astype("float32"))[:, None, None, :] * -1e9,
        m, _name="AttnMask")


def _first_token(x):
    return autograd._op(lambda v: v[:, 0, :], x, _name="FirstToken")


class BertForMaskedLM(model.Model):
    """MLM head over the trunk; the config #4 training workload."""

    def __init__(self, cfg=None, plan=None):
        super().__init__()
        self.cfg = cfg or BertConfig.base()
        self.bert = BertModel(self.cfg, plan)
        self.transform = layer.Linear(self.cfg.hidden_size)
        self.ln = layer.LayerNorm(self.cfg.layer_norm_eps)
        if plan is not None:
            from ..parallel.tensor_parallel import ColumnParallelLinear

            self.decoder = ColumnParallelLinear(self.cfg.vocab_size, plan,
                                                gather_output=True)
        else:
            self.decoder = layer.Linear(self.cfg.vocab_size)
        self.softmax_cross_entropy = layer.SoftMaxCrossEntropy()

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        # call .forward explicitly: Model.__call__ would route a nested
        # Model to train_one_batch while training
        seq, _ = self.bert.forward(input_ids, token_type_ids, attention_mask)
        h = autograd.gelu(self.transform(seq))
        h = self.ln(h)
        logits = self.decoder(h)
        return logits

    def train_one_batch(self, input_ids, labels, dist_option="plain",
                        spars=None):
        from .common import apply_dist_option

        logits = self.forward(input_ids)
        b, s, v = logits.shape
        loss = self.softmax_cross_entropy(
            autograd.reshape(logits, (b * s, v)),
            autograd.reshape(labels, (b * s,)))
        apply_dist_option(self.optimizer, loss, dist_option, spars)
        return logits, loss


def create_model(size="base", plan=None, **kw):
    cfg = BertConfig.tiny(**kw) if size == "tiny" else BertConfig.base(**kw)
    return BertForMaskedLM(cfg, plan)
