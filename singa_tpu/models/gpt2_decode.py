"""KV-cached incremental decoding for GPT-2 (TPU-native inference path).

The reference has no inference machinery at all (its ONNX examples run
full forwards — SURVEY.md §2.4); the round-2 ``generate`` here did the
fixed-window equivalent: one FULL-context forward per emitted token,
O(S²·T) total attention work.  This module is the idiomatic TPU design:

* **prefill** — one causal forward over the (padded) prompt that also
  returns every layer's K/V, written into a preallocated
  ``(L, B, H, ctx, D)`` cache;
* **decode** — a single ``lax.scan`` over new tokens, each step
  attending its one-query block against the cache (masked to the live
  positions) and writing its K/V at the current position with
  ``lax.dynamic_update_slice`` — O(S·D) per token, static shapes, ONE
  compiled executable for the whole generation.  The scan body is
  UNROLLED 4× by default (round 5): XLA schedules 4 sequential token
  steps per loop iteration, which amortizes loop overhead and
  pipelines the weight reads — measured 2633 → 4483 tok/s (+70%) at
  the bench config on the v5e (unroll=8 adds only +3.6% more for 2×
  the compile time).

The math mirrors the layer stack exactly (same fp32-stat LayerNorm,
same tanh-approx gelu, same scale placement), and
``tests/test_gpt2.py`` asserts the cached step's logits equal the full
forward's to tolerance at every position.  Batched (possibly ragged)
prompts decode lockstep in one executable (`jax.vmap` over the row
core — per-row cache writes lower to scatters), with greedy,
temperature, top-k, and top-p (nucleus) sampling.  Plan-sharded models
decode here too (round 4): extract_params lays the weights out per the
Megatron plan and the jitted generation runs SPMD.  MoE models decode
here as well (round 5): per-token top-k expert routing with no capacity
limit — see extract_params.  GQA models (``GPT2Config(n_kv_head=K)``,
round 5) keep their cache at K heads — the head counts are derived
from the weight widths, and the decode step contracts each K/V head
against its query group without materializing a repeat
(``_block_decode``).  Sliding-window models
(``GPT2Config(attn_window=W)``, round 5) decode from an O(W) ROLLING
cache — position p lives in slot p % W — and the int8 cache
(``cache_dtype="int8"``) stores (values, scales) tuples with the
scales folded into the score/prob contractions; all of these compose.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def extract_params(m, dtype=None):
    """Pull the GPT2LMHead weight pytree (raw jax arrays).
    ``dtype`` (e.g. jnp.bfloat16) casts the float weights for inference
    — decode is weight-read-bound, so bf16 weights ≈ double the
    steady-state tokens/sec (measured 803 → 1604 on the v5e at the
    bench config); LayerNorm statistics stay fp32 inside _ln either
    way.

    Plan-sharded models work too (round 4): each weight is device_put
    with its layer's partition spec (Megatron column/row layout), and
    since the decode math is pure jnp, the jitted generation runs SPMD
    — GSPMD inserts the same collectives the training forward uses.

    MoE blocks (round 5): the expert weights come out as stacked
    (E, ...) arrays under ``moe_*`` keys and decode routes each token
    to its top-k experts with NO capacity limit (capacity is a
    static-shape training-efficiency device; at inference every token
    gets its chosen experts).  Token-parity with the windowed sampler
    therefore holds exactly when the windowed forward drops nothing —
    the regime its capacity_factor is tuned for.

    SESSION CACHE (round 5): the extracted (cast, plan-laid-out)
    pytree is cached on the model, keyed by ``dtype``/plan and the
    identity of every state buffer — repeated ``generate``/
    ``generate_beam`` calls on an unchanged model skip the per-call
    re-cast/re-shard (a full weight upload per request under a plan).
    Any state mutation (a training step, ``set_states``,
    ``load_states``) replaces the underlying ``jax.Array`` buffers, so
    the identity signature misses and the cache rebuilds; since round 6
    ``Model.set_states`` additionally DROPS the entry eagerly, so the
    superseded weight copy the entry's strong refs pinned is released
    at swap time, not at the next generate call."""
    bufs = [t_.data for _, t_ in sorted(m.get_states().items())]
    sig = (str(dtype), id(m.plan), tuple(id(b) for b in bufs))
    cache = getattr(m, "_decode_param_cache", None)
    if cache is not None and cache[0] == sig:
        return cache[2]
    t = m.transformer
    blocks = []
    for blk in t.blocks:
        mlp = blk.mlp
        if mlp is None:
            raise RuntimeError("model not initialized: call compile() or "
                               "run one forward first")
        common = dict(
            ln1_s=blk.ln1.scale.data, ln1_b=blk.ln1.bias.data,
            wq=blk.attn.q_proj.W.data, bq=blk.attn.q_proj.b.data,
            wk=blk.attn.k_proj.W.data, bk=blk.attn.k_proj.b.data,
            wv=blk.attn.v_proj.W.data, bv=blk.attn.v_proj.b.data,
            wo=blk.attn.out_proj.W.data, bo=blk.attn.out_proj.b.data,
            ln2_s=blk.ln2.scale.data, ln2_b=blk.ln2.bias.data,
        )
        if hasattr(mlp, "fc1"):
            common.update(w1=mlp.fc1.W.data, b1=mlp.fc1.b.data,
                          w2=mlp.fc2.W.data, b2=mlp.fc2.b.data)
        elif hasattr(mlp, "Wg"):  # MoEFFN expert-routed block
            common.update(
                moe_wg=mlp.Wg.data,
                moe_w1=mlp.W1.data, moe_b1=mlp.b1.data,
                moe_w2=mlp.W2.data, moe_b2=mlp.b2.data)
        else:
            raise ValueError(
                f"KV-cache decode does not recognize MLP type "
                f"{type(mlp).__name__}")
        blocks.append(common)
    head = None if m.cfg.tie_weights else m.lm_head.W.data
    params = dict(wte=t.wte.W.data, wpe=t.wpe.W.data, blocks=blocks,
                  lnf_s=t.ln_f.scale.data, lnf_b=t.ln_f.bias.data,
                  head=head)
    if dtype is not None:
        params = jax.tree.map(
            lambda a: a.astype(dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
    if m.plan is not None:
        params = _shard_params(m, params)
    # the strong refs to the keyed buffers make the id() signature
    # sound: while this entry lives, no new array can recycle their ids
    m._decode_param_cache = (sig, bufs, params)
    return params


def _shard_params(m, params):
    """Lay the extracted weights out per the model's sharding plan so
    the jitted decode runs SPMD over the mesh (weights loaded via
    set_states may sit unsharded on one device otherwise).  Spec
    resolution delegates to ShardingPlan.spec_for_state — the full
    three-tier rule (partition_spec attr, then the plan's regex rules
    by state name, then replicated), not just the attr."""
    plan = m.plan
    t = m.transformer
    names = {id(v): k for k, v in m.get_states().items()}

    def put(arr, owner):
        spec = plan.spec_for_state(names.get(id(owner), ""), owner)
        return jax.device_put(arr, plan.sharding(spec))

    out = dict(params)
    out["wte"] = put(params["wte"], t.wte.W)
    out["wpe"] = put(params["wpe"], t.wpe.W)
    out["lnf_s"] = put(params["lnf_s"], t.ln_f.scale)
    out["lnf_b"] = put(params["lnf_b"], t.ln_f.bias)
    if params["head"] is not None:
        out["head"] = put(params["head"], m.lm_head.W)
    new_blocks = []
    for blk, p in zip(t.blocks, params["blocks"]):
        owners = dict(
            ln1_s=blk.ln1.scale, ln1_b=blk.ln1.bias,
            wq=blk.attn.q_proj.W, bq=blk.attn.q_proj.b,
            wk=blk.attn.k_proj.W, bk=blk.attn.k_proj.b,
            wv=blk.attn.v_proj.W, bv=blk.attn.v_proj.b,
            wo=blk.attn.out_proj.W, bo=blk.attn.out_proj.b,
            ln2_s=blk.ln2.scale, ln2_b=blk.ln2.bias)
        if hasattr(blk.mlp, "fc1"):
            owners.update(w1=blk.mlp.fc1.W, b1=blk.mlp.fc1.b,
                          w2=blk.mlp.fc2.W, b2=blk.mlp.fc2.b)
        else:  # MoEFFN: expert weights carry P(EXPERT, ...) specs
            owners.update(moe_wg=blk.mlp.Wg,
                          moe_w1=blk.mlp.W1, moe_b1=blk.mlp.b1,
                          moe_w2=blk.mlp.W2, moe_b2=blk.mlp.b2)
        new_blocks.append({k: put(v, owners[k]) for k, v in p.items()})
    out["blocks"] = new_blocks
    return out


def _ln(x, s, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * s + b).astype(x.dtype)


# -- tensor-parallel threading (serve/tp.py) ---------------------------------
# Every block function below takes ``tp_axis``/``tp_world`` kwargs
# (default None/1).  Unset, each expression is LITERALLY the pre-TP
# one — ``(a @ wo) + bo`` with no reduction reordered — so the
# single-device paths stay bit-identical.  Set (inside a shard_map
# over a ``tp`` mesh axis), the attention/MLP weights arrive COLUMN/
# ROW-sharded Megatron-style (parallel/tensor_parallel.py's layout,
# specs from ``decode_param_specs``): q/k/v/fc1 are column-local (the
# per-shard head/column slice needs no communication), and the two
# row-parallel products — attention out-proj and MLP fc2 — each close
# with ONE psum here, bias added AFTER the reduction (added per shard
# it would be multiplied by the world size).

def _tp_psum(y, axis, world):
    """All-reduce a row-parallel partial product over the ``axis``
    mesh axis; ``axis=None`` returns ``y`` untouched (the serial
    path).  The collective is recorded through the communicator's
    observe hook at trace time — op, payload bytes, axis name, and
    mesh size — so TP-serve psums are attributable in Chrome traces
    next to the training collectives."""
    if axis is None:
        return y
    from ..parallel.communicator import _record_collective

    _record_collective("psum", [y], axis=axis, world=world)
    return jax.lax.psum(y, axis)


# -- int8 KV cache (round 5) ------------------------------------------------
# The GQA measurement (PERF.md §8) showed decode tokens/sec scales
# near-linearly with cache BYTES — so halving bytes/element is the same
# lever: the cache stores (int8 values, one f32 scale per (token, head)
# row over D), cutting cache traffic ~2× vs bf16.  XLA fuses the
# dequantize into the score/value einsums, so HBM sees int8 + scales
# only.  A quantized cache is a (values, scales) tuple everywhere a
# dense cache is an array; the helpers below keep every decode path
# shape-agnostic between the two.

def _quantize_kv(x):
    """(…, D) float -> ((…, D) int8, (…) f32 scale), symmetric per-row."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def _dequantize_kv(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _cache_layer(c, li):
    """Layer li of a stacked cache (dense array or (values, scales))."""
    return (c[0][li], c[1][li]) if isinstance(c, tuple) else c[li]


def _cache_stack(layers):
    if isinstance(layers[0], tuple):
        return (jnp.stack([l[0] for l in layers]),
                jnp.stack([l[1] for l in layers]))
    return jnp.stack(layers)


def _attn_full(q, k, v, n_head, start=None, window=None, tp_world=1):
    """Causal attention over the full (B, S, E) prefill block.
    ``start``: optional (B,) first-live window position per row
    (left-padded batch) — keys before it are masked out.  GQA models
    arrive with k/v narrower than q (n_kv_head·D wide — the head count
    is derived from the widths, never threaded); each K/V head is
    broadcast over its query-head group, matching the training stack's
    RepeatKV (parallel/tensor_parallel.py ParallelMHA).  ``window``:
    sliding-window band (query i sees keys [i-window+1, i]), matching
    the training stack's banded _sdpa.  ``tp_world`` > 1: q/k/v carry
    only this shard's heads (1/tp_world of the widths) — attention is
    head-local, so the per-shard math below is exactly the serial
    math on the head slice."""
    b, s, e = q.shape
    d = (e * tp_world) // n_head
    n_local = n_head // tp_world
    n_kv = k.shape[-1] // d

    def heads(t, nh):
        return t.reshape(b, s, nh, d).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q, n_local), heads(k, n_kv), heads(v, n_kv)
    if n_kv != n_local:
        kh = jnp.repeat(kh, n_local // n_kv, axis=1)
        vh = jnp.repeat(vh, n_local // n_kv, axis=1)
    sc = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / math.sqrt(d)
    cm = jnp.tril(jnp.ones((s, s), bool))
    if window is not None:
        i = jnp.arange(s)[:, None]
        j = jnp.arange(s)[None, :]
        cm = cm & (i - j < window)
    cm = cm[None, None]
    if start is not None:
        live = jnp.arange(s)[None, :] >= start[:, None]  # (B, S) keys
        cm = cm & live[:, None, None, :]
        # fully-masked pad-query rows degrade to uniform attention over
        # NEG_INF scores (finite garbage, never read) — NEG_INF is -1e30,
        # not -inf, so no NaNs propagate
    sc = jnp.where(cm, sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return o.transpose(0, 2, 1, 3).reshape(b, s, e)


def _block_prefill(x, p, n_head, eps, start=None, moe_top_k=2,
                   window=None, tp_axis=None, tp_world=1, ep=None):
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = h @ p["wq"] + p["bq"]
    k = h @ p["wk"] + p["bk"]
    v = h @ p["wv"] + p["bv"]
    a = _attn_full(q, k, v, n_head, start=start, window=window,
                   tp_world=tp_world)
    x = x + (_tp_psum(a @ p["wo"], tp_axis, tp_world) + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + _mlp(h, p, moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
                 ep=ep)
    return x, k, v


def _block_decode(x, p, k_cache, v_cache, pos, n_head, eps, start=None,
                  moe_top_k=2, window=None, tp_axis=None, tp_world=1,
                  ep=None):
    """x: (B, 1, E); k/v_cache: (B, H_kv, ctx, D) with this step's K/V
    already written at ``pos``.  Attends to positions <= pos (and
    >= ``start`` per row for left-padded batches).

    GQA (H_kv < n_head): the cache stays at H_kv heads — THE point of
    GQA at decode, n_head/H_kv× less cache traffic per token on a
    cache-read-bound loop — and the query block reshapes to
    (B, H_kv, G, D) so each K/V head serves its G-query group in one
    grouped einsum (no repeat materialized).  H_kv == n_head makes
    G=1 and this is exactly the ungrouped math.

    int8 caches arrive as (values, scales) tuples: reads dequantize
    into the einsums (XLA fuses — HBM traffic stays int8), writes
    quantize this step's K/V row.

    ``window`` (static): ROLLING cache of exactly ``window`` slots —
    position pos lives in slot pos % window, so each write overwrites
    the slot that just fell out of the band, and the live mask
    reconstructs each slot's position from (pos, slot index) with no
    extra state.  O(window) cache reads per token regardless of how
    long the generation runs."""
    quant = isinstance(k_cache, tuple)
    kq = k_cache[0] if quant else k_cache
    b, _, e = x.shape
    d = e // n_head
    n_kv = kq.shape[1]          # LOCAL kv heads (H_kv / tp_world)
    g = n_head // (n_kv * tp_world)
    ctx = kq.shape[2]
    if window is not None:
        assert ctx == window, (
            f"rolling cache dim {ctx} != window {window}")
        slot = pos % window
    else:
        slot = pos
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = (h @ p["wq"] + p["bq"]).reshape(b, n_kv, g, d)
    k_new = (h @ p["wk"] + p["bk"]).reshape(b, n_kv, 1, d)
    v_new = (h @ p["wv"] + p["bv"]).reshape(b, n_kv, 1, d)
    if quant:
        # scale-FOLDED quantized attention: contract against the raw
        # int8 arrays (the convert rides the einsum operand; no
        # dequantized cache is materialized) and apply the per-token
        # scales outside the contractions —
        #   scores[t] = (q · k8[t]) · kscale[t];
        #   out = Σ_t (p[t]·vscale[t]) · v8[t]
        (kqv, ksc), (vqv, vsc) = k_cache, v_cache
        k8, k8s = _quantize_kv(k_new)
        v8, v8s = _quantize_kv(v_new)
        kqv = jax.lax.dynamic_update_slice(kqv, k8, (0, 0, slot, 0))
        ksc = jax.lax.dynamic_update_slice(ksc, k8s, (0, 0, slot))
        vqv = jax.lax.dynamic_update_slice(vqv, v8, (0, 0, slot, 0))
        vsc = jax.lax.dynamic_update_slice(vsc, v8s, (0, 0, slot))
        k_cache, v_cache = (kqv, ksc), (vqv, vsc)
        sc = jnp.einsum("bkgd,bktd->bkgt", q, kqv.astype(x.dtype))
        sc = sc * ksc[:, :, None, :].astype(sc.dtype) / math.sqrt(d)
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k_new,
                                               (0, 0, slot, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v_new,
                                               (0, 0, slot, 0))
        sc = jnp.einsum("bkgd,bktd->bkgt", q, k_cache) / math.sqrt(d)
    if window is not None:
        # slot s currently holds position pos - ((pos - s) mod window)
        # (<= pos, within the band by construction; negative = never
        # written)
        p_slot = pos - ((pos - jnp.arange(ctx)) % window)
        live = (p_slot >= 0)[None, None, None, :]
        if start is not None:
            live = live & (p_slot[None, None, None, :]
                           >= start[:, None, None, None])
    else:
        live = jnp.arange(ctx)[None, None, None, :] <= pos
        if start is not None:
            live = live & (jnp.arange(ctx)[None, None, None, :]
                           >= start[:, None, None, None])
    sc = jnp.where(live, sc, NEG_INF)
    p_attn = jax.nn.softmax(sc, axis=-1)
    if quant:
        pv = p_attn * vsc[:, :, None, :].astype(p_attn.dtype)
        a = jnp.einsum("bkgt,bktd->bkgd", pv, vqv.astype(x.dtype))
    else:
        a = jnp.einsum("bkgt,bktd->bkgd", p_attn, v_cache)
    # (B, H_kv, G, D) in head-major order == (B, 1, E) concat of heads
    # (this shard's slice of it when tp_world > 1)
    a = a.reshape(b, 1, e // tp_world)
    x = x + (_tp_psum(a @ p["wo"], tp_axis, tp_world) + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + _mlp(h, p, moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
                 ep=ep)
    return x, k_cache, v_cache


def _moe_weights(probs, top_k):
    """Per-token combine weights (…, E) from router softmax ``probs``
    (f32), zeros except the top-k experts.  Mirrors parallel/moe.py's
    gating exactly in the no-drop regime: top-1 keeps the RAW chosen
    prob (Switch); top-2 renormalizes the two gates to sum 1
    (GShard)."""
    if top_k not in (1, 2):
        raise ValueError("moe_top_k must be 1 (Switch) or 2 (GShard), "
                         f"got {top_k}")
    e = probs.shape[-1]
    m1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                        dtype=probs.dtype)
    g1 = jnp.sum(probs * m1, axis=-1)
    if top_k == 1:
        return m1 * g1[..., None]
    p2 = probs * (1.0 - m1)
    m2 = jax.nn.one_hot(jnp.argmax(p2, axis=-1), e, dtype=probs.dtype)
    g2 = jnp.sum(p2 * m2, axis=-1)
    den = g1 + g2
    den = jnp.where(den <= 0.0, 1.0, den)
    return (m1 * (g1 / den)[..., None] + m2 * (g2 / den)[..., None])


def _moe_ffn(h, p, top_k):
    """Capacity-free MoE FFN for decode: route each of the (B, S, D)
    post-LN tokens to its top-k experts and mask-and-sum over a python
    loop of per-expert GEMMs (E dense MLPs — each big enough for the
    MXU; memory stays O(B·S·F), not O(B·S·E·F)).  No capacity limit:
    see extract_params."""
    probs = jax.nn.softmax(
        (h @ p["moe_wg"].astype(h.dtype)).astype(jnp.float32), axis=-1)
    w = _moe_weights(probs, top_k).astype(h.dtype)          # (B, S, E)
    y = jnp.zeros_like(h)
    for e in range(p["moe_w1"].shape[0]):
        he = jax.nn.gelu(h @ p["moe_w1"][e] + p["moe_b1"][e])
        y = y + w[..., e:e + 1] * (he @ p["moe_w2"][e] + p["moe_b2"][e])
    return y


# -- expert-parallel MoE FFN (serve/ep.py) -----------------------------------
# The serve EP backend runs every dispatch under a shard_map over a
# 2-D (ep, tp) mesh with the stacked expert weights sharded on their
# leading axis.  The FFN below is the GShard formulation restated for
# replicated decode activations: routing + capacity run identically on
# every rank (probs are replicated), each rank computes only its
# RESIDENT experts' contributions through the capacity-shaped
# dispatch/combine one-hots (parallel/moe.py's — the training layer's
# routing math, reused verbatim), and ONE psum over the ep axis sums
# each token's top-k expert outputs — the degenerate all-to-all for
# replicated tokens (the dispatch half is free because every rank
# already holds every token; only the combine reduces).
#
# Exactness: with ``cap_factor=None`` the capacity is the token count —
# nothing ever drops, and per-token outputs equal `_moe_ffn`'s exactly
# up to float summation order (the ep psum — the same near-tie caveat
# as the TP psum).  A FINITE cap_factor is the GShard capacity mode:
# per-dispatch token groups bound each expert's buffer, over-capacity
# assignments are DROPPED — their combine weight is zero, so the
# block's residual path carries the token (never a zeroed hidden
# state) — and the drop pattern couples tokens within a dispatch
# (which is why the engine refuses a finite cap_factor next to the
# prefix cache: chunked and full prefill route different groups, so
# chunk KV would stop being canonical).  Pad lanes of a prefill
# dispatch route like real tokens and consume capacity — deterministic
# but part of the group, documented in docs/SERVING.md.
#
# Observability rides a TRACE-TIME collector: while an ep.py twin body
# is being traced, every `_moe_ffn_ep` application appends its
# (tokens-per-expert, dropped) arrays, and the twin wrapper folds them
# into two extra replicated outputs (`serve.ep.expert_tokens{expert=}`
# / the dropped-token counter).  One thread-local stack — the wrapper
# consumes the tracers inside the same trace that made them.

_EP_COLLECT = __import__("threading").local()


class _ep_collecting:
    """Context manager arming the EP-stats collector for one row/body
    trace; yields the list `_moe_ffn_ep` appends (counts, dropped)
    tracer pairs to."""

    def __enter__(self):
        stack = getattr(_EP_COLLECT, "stack", None)
        if stack is None:
            stack = _EP_COLLECT.stack = []
        self._rec = []
        stack.append(self._rec)
        return self._rec

    def __exit__(self, *exc):
        _EP_COLLECT.stack.pop()
        return False


def _ep_record(counts, dropped):
    stack = getattr(_EP_COLLECT, "stack", None)
    if stack:
        stack[-1].append((counts, dropped))


def _moe_ffn_ep(h, p, top_k, ep):
    """Expert-parallel MoE FFN: ``ep = (axis, world, cap_factor)`` —
    the mesh axis the stacked expert weights shard over, its size, and
    the GShard capacity factor (None = capacity == tokens, drop-free).
    ``p['moe_w1']``&co arrive as this rank's (E/world, ...) slices
    under shard_map; ``moe_wg`` is replicated."""
    from ..parallel import moe as _moe

    axis, world, cap_factor = ep
    b, s, dm = h.shape
    n = b * s
    e = p["moe_wg"].shape[-1]
    probs = jax.nn.softmax(
        (h @ p["moe_wg"].astype(h.dtype)).astype(jnp.float32),
        axis=-1).reshape(n, e)
    cap = (n if cap_factor is None
           else max(1, int(math.ceil(top_k * n / e * cap_factor))))
    if top_k == 2:
        dispatch, combine, _ = _moe._top2_dispatch(probs, cap)
    elif top_k == 1:
        dispatch, combine, _ = _moe._top1_dispatch(probs, cap)
    else:
        raise ValueError("moe_top_k must be 1 (Switch) or 2 (GShard), "
                         f"got {top_k}")
    _ep_record(*_moe.dispatch_load(dispatch, top_k))
    rank = jax.lax.axis_index(axis)
    e_loc = e // world
    d_l = jax.lax.dynamic_slice_in_dim(
        dispatch, rank * e_loc, e_loc, axis=1).astype(h.dtype)
    c_l = jax.lax.dynamic_slice_in_dim(
        combine, rank * e_loc, e_loc, axis=1).astype(h.dtype)
    ht = h.reshape(n, dm)
    # dispatch: tokens -> this rank's (E_loc, C, D) expert buffers
    xin = jnp.einsum("nec,nd->ecd", d_l, ht)
    hh = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, p["moe_w1"])
                     + p["moe_b1"][:, None, :])
    out = jnp.einsum("ecf,efd->ecd", hh, p["moe_w2"]) \
        + p["moe_b2"][:, None, :]
    # combine locally (non-resident experts weight zero on this rank),
    # then ONE psum over ep sums each token's top-k contributions —
    # recorded through the communicator hook like the TP psums
    y = jnp.einsum("nec,ecd->nd", c_l, out)
    return _tp_psum(y, axis, world).reshape(b, s, dm)


def _mlp(h, p, moe_top_k, tp_axis=None, tp_world=1, ep=None):
    """The block's feed-forward: dense two-layer gelu MLP, or the
    expert-routed MoE when the block carries ``moe_*`` weights.  Under
    ``tp_axis`` the dense path is column-fc1 / row-fc2 with ONE psum
    (Megatron); MoE blocks shard over the EXPERT axis instead —
    ``ep = (axis, world, cap_factor)`` threads the serve EP backend's
    mesh through (singa_tpu/serve/ep.py), and an MoE block under
    ``tp_axis`` WITHOUT an ep axis is rejected with a pointer at the
    ``serve(ep=)`` path."""
    if "moe_wg" in p:
        if ep is not None:
            return _moe_ffn_ep(h, p, moe_top_k, ep)
        if tp_axis is not None:
            raise NotImplementedError(
                "MoE blocks are not tensor-parallel: expert weights "
                "shard over the expert axis — serve this model with "
                "model.serve(ep=EPConfig(ep=, tp=)) "
                "(singa_tpu/serve/ep.py)")
        return _moe_ffn(h, p, moe_top_k)
    return _tp_psum(jax.nn.gelu(h @ p["w1"] + p["b1"]) @ p["w2"],
                    tp_axis, tp_world) + p["b2"]


def _logits(x, params):
    head = params["head"]
    if head is None:
        return x @ params["wte"].T
    return x @ head


def prefill(params, ids, n_head, eps, start=None, moe_top_k=2,
            quant_cache=False, window=None, prompt_end=None,
            rolling=True, tp_axis=None, tp_world=1, ep=None):
    """ids: (B, Sp) int32 (padded prompt).  Returns (hidden, k_caches,
    v_caches): hidden is the final-LN (B, Sp, E) — the caller picks the
    rows it needs BEFORE the vocab matmul (materializing (Sp, V) logits
    for all pad positions would double prefill cost) — and caches are
    (L, B, H, Sp, D); pad positions hold garbage K/V that decode never
    attends to (mask is position-indexed).

    ``start`` (B,): LEFT-padded batch — row i's prompt occupies window
    positions [start_i, Sp_shared).  Row-relative position embeddings
    (window pos − start_i, clipped for pads) and a per-row key mask make
    the math identical to a right-padded row shifted by start_i, which
    is what puts RAGGED batches on the shared-position fast path."""
    b, sp = ids.shape
    if start is None:
        # (1, Sp) gather broadcasts in the add — one wpe read, not B
        pos = jnp.arange(sp, dtype=jnp.int32)[None, :]
    else:
        pos = jnp.clip(jnp.arange(sp, dtype=jnp.int32)[None, :]
                       - start[:, None], 0, None)
    x = jnp.take(params["wte"], ids, axis=0) + \
        jnp.take(params["wpe"], pos, axis=0)
    roll = None
    if window is not None and window < sp and rolling:
        # ROLLING cache (sliding window): slot w <- the last prompt
        # position p < prompt_end with p ≡ w (mod window); decode
        # writes position pos into slot pos % window, so the slot
        # mapping must be position-mod from the start.  Gathering by
        # prompt_end (not the padded width sp) keeps right-pad
        # garbage from overwriting real prompt K/V in its slot.
        # ``rolling=False`` keeps the banded attention mask but a
        # LINEAR position-indexed cache — the paged serve engine's
        # windowed mode (block tables address positions directly and
        # drop out-of-window blocks; the roll would scramble its
        # block arithmetic).  The K/V VALUES are identical either
        # way: the roll is a pure reorder after they are computed.
        pe_ = (sp if prompt_end is None else prompt_end) - 1
        w = jnp.arange(window)
        roll = jnp.clip(pe_ - ((pe_ - w) % window), 0, sp - 1)
    ks, vs = [], []
    for p in params["blocks"]:
        x, k, v = _block_prefill(x, p, n_head, eps, start=start,
                                 moe_top_k=moe_top_k, window=window,
                                 tp_axis=tp_axis, tp_world=tp_world,
                                 ep=ep)
        e = x.shape[-1]
        d = e // n_head
        n_kv = k.shape[-1] // d  # GQA caches hold n_kv_head heads
        kh = k.reshape(b, sp, n_kv, d).transpose(0, 2, 1, 3)
        vh = v.reshape(b, sp, n_kv, d).transpose(0, 2, 1, 3)
        if roll is not None:
            kh = jnp.take(kh, roll, axis=2)
            vh = jnp.take(vh, roll, axis=2)
        if quant_cache:
            kh, vh = _quantize_kv(kh), _quantize_kv(vh)
        ks.append(kh)
        vs.append(vh)
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return x, _cache_stack(ks), _cache_stack(vs)


def _advance_one(params, x, kc, vc, pos, n_head, eps, start=None,
                 moe_top_k=2, window=None, tp_axis=None, tp_world=1,
                 ep=None):
    """Advance one decode step through every block: x (B, 1, E) at
    position ``pos`` against caches (L, B, H, ctx, D).  Returns
    ((B, V) logits, new kc, new vc).  Shared by sampling
    (_generate_row), the left-padded ragged path, and beam search so
    the paths cannot drift."""
    new_kc, new_vc = [], []
    for li, p in enumerate(params["blocks"]):
        x, kl, vl = _block_decode(x, p, _cache_layer(kc, li),
                                  _cache_layer(vc, li), pos, n_head,
                                  eps, start=start, moe_top_k=moe_top_k,
                                  window=window, tp_axis=tp_axis,
                                  tp_world=tp_world, ep=ep)
        new_kc.append(kl)
        new_vc.append(vl)
    kc = _cache_stack(new_kc)
    vc = _cache_stack(new_vc)
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return _logits(x, params)[:, 0], kc, vc


def decode_step(params, x, kc, vc, pos, n_head, eps, *, start=None,
                moe_top_k=2, window=None, tp_axis=None, tp_world=1,
                ep=None):
    """PUBLIC single-step decode core with an EXTERNALIZED cache carry
    (the serve engine's contract; round 6).  The generation loops in
    this module own their KV cache inside a ``lax.scan`` carry; an
    iteration-level scheduler (singa_tpu/serve) instead owns the cache
    arena across steps and calls this once per engine iteration.

    ``x``: (B, 1, E) embedded inputs at position ``pos`` (traced
    scalar, or per-row under vmap); ``kc``/``vc``: (L, B, H_kv, ctx, D)
    caches — this step's K/V rows are written at ``pos`` and the new
    caches RETURNED (functional carry; the caller rebinds).  Returns
    ``((B, V) logits, new kc, new vc)``.  Exactly the math every
    sampling/beam/speculative path here uses (_advance_one), so an
    external cache owner cannot drift from ``generate``.

    ``tp_axis``/``tp_world`` (serve/tp.py): inside a shard_map over a
    ``tp`` mesh axis with Megatron-sharded params and head-sharded
    caches, the step runs one psum per attention output and per MLP
    fc2 and returns replicated logits.  Defaults leave the serial
    math bit-identical."""
    return _advance_one(params, x, kc, vc, pos, n_head, eps,
                        start=start, moe_top_k=moe_top_k, window=window,
                        tp_axis=tp_axis, tp_world=tp_world, ep=ep)


def _block_chunk(x, p, k_cache, v_cache, pos, n_head, eps,
                 moe_top_k=2, window=None, tp_axis=None, tp_world=1,
                 ep=None):
    """Chunked cache advance: x (B, K, E) are K consecutive tokens at
    positions pos..pos+K-1.  Writes all K K/V rows in one contiguous
    dynamic_update_slice and attends the K queries against the cache
    with a per-query position mask (query i sees positions
    <= pos + i).  The speculative verify step: ONE cache read serves
    K token positions, which is where the speedup over K sequential
    decode steps comes from on a cache-read-bound loop.  Dense or
    int8 caches; GQA via the same grouped layout as _block_decode.
    ``window``: sliding-window band — query i additionally masks
    positions <= pos + i - window (LINEAR cache, the paged serve
    engine's windowed chunk prefill; the rolling-cache decode path
    is _block_decode's)."""
    quant = isinstance(k_cache, tuple)
    kq0 = k_cache[0] if quant else k_cache
    b, klen, e = x.shape
    d = e // n_head
    n_kv = kq0.shape[1]         # LOCAL kv heads (H_kv / tp_world)
    g = n_head // (n_kv * tp_world)
    ctx = kq0.shape[2]
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = (h @ p["wq"] + p["bq"]).reshape(b, klen, n_kv, g, d) \
        .transpose(0, 2, 3, 1, 4)                       # (B,kv,g,K,d)
    k_new = (h @ p["wk"] + p["bk"]).reshape(b, klen, n_kv, d) \
        .transpose(0, 2, 1, 3)                          # (B,kv,K,d)
    v_new = (h @ p["wv"] + p["bv"]).reshape(b, klen, n_kv, d) \
        .transpose(0, 2, 1, 3)
    if quant:
        (kqv, ksc), (vqv, vsc) = k_cache, v_cache
        k8, k8s = _quantize_kv(k_new)
        v8, v8s = _quantize_kv(v_new)
        kqv = jax.lax.dynamic_update_slice(kqv, k8, (0, 0, pos, 0))
        ksc = jax.lax.dynamic_update_slice(ksc, k8s, (0, 0, pos))
        vqv = jax.lax.dynamic_update_slice(vqv, v8, (0, 0, pos, 0))
        vsc = jax.lax.dynamic_update_slice(vsc, v8s, (0, 0, pos))
        k_cache, v_cache = (kqv, ksc), (vqv, vsc)
        sc = jnp.einsum("bkgqd,bktd->bkgqt", q, kqv.astype(x.dtype))
        sc = sc * ksc[:, :, None, None, :].astype(sc.dtype) \
            / math.sqrt(d)
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k_new,
                                               (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v_new,
                                               (0, 0, pos, 0))
        sc = jnp.einsum("bkgqd,bktd->bkgqt", q, k_cache) \
            / math.sqrt(d)
    live = (jnp.arange(ctx)[None, :]
            <= (pos + jnp.arange(klen))[:, None])       # (K, ctx)
    if window is not None:
        live = live & (jnp.arange(ctx)[None, :]
                       > (pos + jnp.arange(klen))[:, None] - window)
    sc = jnp.where(live[None, None, None], sc, NEG_INF)
    p_attn = jax.nn.softmax(sc, axis=-1)
    if quant:
        pv = p_attn * vsc[:, :, None, None, :].astype(p_attn.dtype)
        a = jnp.einsum("bkgqt,bktd->bkgqd", pv, vqv.astype(x.dtype))
    else:
        a = jnp.einsum("bkgqt,bktd->bkgqd", p_attn, v_cache)
    a = a.transpose(0, 3, 1, 2, 4).reshape(b, klen, e // tp_world)
    x = x + (_tp_psum(a @ p["wo"], tp_axis, tp_world) + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + _mlp(h, p, moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
                 ep=ep)
    return x, k_cache, v_cache


def prefill_chunk(params, x, kc, vc, pos, n_head, eps, *, moe_top_k=2,
                  window=None, tp_axis=None, tp_world=1, ep=None):
    """PUBLIC offset-prefill entry (the prefix cache's contract;
    serve.prefix round).  Advance every layer by a K-token chunk —
    ``x``: (B, K, E) embedded inputs at positions ``pos..pos+K-1``
    (``pos`` traced) against caches (L, B, H_kv, ctx, D) that already
    hold K/V for positions < ``pos``.  Writes the chunk's K/V rows at
    ``pos`` and returns ``((B, K, E) final-LN hidden, new kc, new vc)``
    — hidden, NOT logits, so a caller prefilling from a cached-prefix
    divergence boundary projects only the row it samples from instead
    of paying a (K, V) vocab matmul per chunk.

    Exactness: on this backend a chunked advance over [pos, pos+K) on
    top of full-prefill K/V produces K/V and hidden rows BITWISE equal
    to the full ``prefill`` of the same row (every op is row-independent
    over the position axis with identical per-row reduction structure;
    pinned by tests/test_prefix.py) — which is what lets the serve
    engine's warm-prefix admissions emit byte-identical token streams
    to cold prefill.  The paged serve arena (serve/paged.py) leans on
    the same guarantee for its zero-copy donation path: a retiring
    slot's prompt blocks hold prefill/chunk output, so the radix tree
    adopts them in place.  NOTE the guarantee is about DENSE rows:
    with a quantized (int8) cache this function is self-consistent —
    the same chunk over the same quantized cache reproduces itself
    bitwise — but the hidden states attend DEQUANTIZED keys where the
    full ``prefill``'s attend float ones, which is why int8 engines
    with a prefix cache route every admission (cold included) through
    the chunked path (engine._admit)."""
    new_kc, new_vc = [], []
    for li, p in enumerate(params["blocks"]):
        x, kl, vl = _block_chunk(x, p, _cache_layer(kc, li),
                                 _cache_layer(vc, li), pos, n_head,
                                 eps, moe_top_k=moe_top_k,
                                 window=window,
                                 tp_axis=tp_axis, tp_world=tp_world,
                                 ep=ep)
        new_kc.append(kl)
        new_vc.append(vl)
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return x, _cache_stack(new_kc), _cache_stack(new_vc)


def _advance_chunk(params, x, kc, vc, pos, n_head, eps, moe_top_k=2,
                   tp_axis=None, tp_world=1, ep=None):
    """Advance every block by a K-token chunk (x: (B, K, E) embedded
    inputs at positions pos..pos+K-1).  Returns ((B, K, V) logits,
    new kc, new vc).  The speculative verify step — routed through
    :func:`prefill_chunk` so the chunked cache math exists once."""
    x, kc, vc = prefill_chunk(params, x, kc, vc, pos, n_head, eps,
                              moe_top_k=moe_top_k, tp_axis=tp_axis,
                              tp_world=tp_world, ep=ep)
    return _logits(x, params), kc, vc


# -- block-native paged decode attention (the gather-tax round) --------------
# The serve engine's paged pool steps (serve/paged.py) used to gather
# every live slot's blocks into a fixed (max_len)-wide row inside the
# executable before attention ran — a transient O(max_len) workspace a
# real PagedAttention kernel (vLLM) never allocates, and O(max_len)
# attention work whatever the slot's actual length.  The kernel below
# computes flash-style attention DIRECTLY over the block pool with the
# block table as the index structure: a ``lax.fori_loop`` over the
# slot's live blocks with online-softmax accumulation (running max,
# rescaled partial sums — the FlashAttention recurrence), trash-block
# and beyond-``pos`` lanes masked, the current step's K/V attended as
# one extra lane (it is not in the pool yet).  The workspace drops to
# O(block_size) and the loop runs ``ceil(pos / block)`` iterations, so
# long-context slots stop paying for their own padding.
#
# Parity pins (docs/SERVING.md "Paged KV and preemption"): online
# softmax REORDERS the float reduction, so bitwise equality to the
# row-softmax gather path is impossible by construction — the contract
# is (a) token streams identical to the gather path (and therefore to
# the slot engine / offline oracles) away from exact argmax/CDF ties,
# the same caveat TP serving documents for its psum, and (b) per-step
# logits allclose to the gather oracle (tests/test_paged.py pins both,
# plus byte equality of the untouched lanes of every written block —
# the read-modify-write below keeps pool bytes round-tripping).  int8
# pools dequantize PER BLOCK inside the accumulator (the same folded
# scale placement as _block_decode: scores scale by kscale outside the
# int8 contraction, probabilities by vscale before the value einsum).

def _paged_attn(q, pool_k_l, pool_v_l, tbl, p_limit, n_blk, block,
                trash, k_cur, v_cur, cur_mask, scale, window=None,
                blk_lo=None):
    """Online-softmax attention of ``q`` (n_kv, g, Q, d) against one
    slot's paged KV: pool lanes at positions < ``p_limit`` (blocks
    ``tbl[0:n_blk]``; trash lanes masked) plus the current chunk's
    keys ``k_cur``/``v_cur`` (n_kv, Q_k, d, quantized tuples on int8
    pools) under ``cur_mask`` (Q, Q_k) — the chunk's own causal mask.
    Accumulates in f32; returns (n_kv, g, Q, d).

    ``window`` (static): sliding-window band — query i (at position
    ``p_limit + i``) additionally masks pool lanes at positions
    <= p_limit + i - window, matching the banded prefill/_block_decode
    semantics on a LINEAR layout.  ``blk_lo`` (traced, default 0):
    loop start — any value <= the first block holding an in-window
    lane (the pool-step wrapper passes the min over live slots, so a
    windowed long chat pays O(window / block) loop iterations instead
    of O(pos / block); out-of-window blocks the engine already
    dropped to the free list sit below it as trash-table entries, so
    correctness never depends on the bound — only work does)."""
    quant = isinstance(pool_k_l, tuple)
    qf = q.astype(jnp.float32)
    n_kv, g, nq, d = qf.shape
    m0 = jnp.full((n_kv, g, nq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n_kv, g, nq), jnp.float32)
    a0 = jnp.zeros((n_kv, g, nq, d), jnp.float32)

    def update(carry, sc, live, vb, vsc):
        m, l, acc = carry
        sc = jnp.where(live, sc, NEG_INF)
        m2 = jnp.maximum(m, jnp.max(sc, axis=-1))
        alpha = jnp.exp(m - m2)
        pr = jnp.exp(sc - m2[..., None])
        # explicit zero, not just NEG_INF scores: a fully-masked block
        # leaves m2 at NEG_INF and exp(NEG_INF - NEG_INF) would be 1
        pr = jnp.where(live, pr, 0.0)
        l2 = l * alpha + jnp.sum(pr, axis=-1)
        if vsc is not None:
            pr = pr * vsc[:, None, None, :]
        upd = jnp.einsum("kgqb,kbd->kgqd", pr, vb.astype(jnp.float32))
        return m2, l2, acc * alpha[..., None] + upd

    def body(j, carry):
        blk = tbl[j]
        if quant:
            kb, ksc = pool_k_l[0][blk], pool_k_l[1][blk]
            vb, vsc = pool_v_l[0][blk], pool_v_l[1][blk]
            sc = jnp.einsum("kgqd,kbd->kgqb", qf,
                            kb.astype(jnp.float32))
            sc = sc * ksc[:, None, None, :] * scale
        else:
            kb, vb, vsc = pool_k_l[blk], pool_v_l[blk], None
            sc = jnp.einsum("kgqd,kbd->kgqb", qf,
                            kb.astype(jnp.float32)) * scale
        lane = j * block + jnp.arange(block)
        live = (lane < p_limit) & (blk != trash)         # (B,)
        if window is not None:
            qpos = p_limit + jnp.arange(nq)              # (Q,)
            live = (live[None, :]
                    & (lane[None, :] > qpos[:, None] - window))
            live = live[None, None]                      # (1,1,Q,B)
        else:
            live = live[None, None, None, :]
        return update(carry, sc, live, vb, vsc)

    lo = jnp.int32(0) if blk_lo is None else blk_lo
    carry = jax.lax.fori_loop(lo, n_blk, body, (m0, l0, a0))
    # the chunk's own keys — computed this step, not yet in the pool
    if quant:
        (kc, kcs), (vc, vcs) = k_cur, v_cur
        sc = jnp.einsum("kgqd,kbd->kgqb", qf, kc.astype(jnp.float32))
        sc = sc * kcs[:, None, None, :] * scale
    else:
        kc, vc, vcs = k_cur, v_cur, None
        sc = jnp.einsum("kgqd,kbd->kgqb", qf,
                        kc.astype(jnp.float32)) * scale
    m, l, acc = update(carry, sc, cur_mask[None, None], vc, vcs)
    return acc / l[..., None]


def _paged_qkv(x, p, n_head, eps):
    """The pre-attention half of a decode/chunk block, shared by the
    paged kernels below: LN, projections, and the grouped-query
    reshape.  x (1, Q, E) -> (q (n_kv, g, Q, d), k/v (n_kv, Q, d))
    with n_kv the LOCAL kv-head count read off the weight widths
    (which is also why no tp_world is needed here — shard-local
    widths carry the layout)."""
    _, nq, e = x.shape
    d = e // n_head
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = h @ p["wq"] + p["bq"]
    k = h @ p["wk"] + p["bk"]
    v = h @ p["wv"] + p["bv"]
    n_kv = k.shape[-1] // d
    g = q.shape[-1] // (n_kv * d)
    q = q.reshape(nq, n_kv, g, d).transpose(1, 2, 0, 3)
    k = k.reshape(nq, n_kv, d).transpose(1, 0, 2)
    v = v.reshape(nq, n_kv, d).transpose(1, 0, 2)
    return q, k, v


def _block_decode_paged(x, p, pool_k_l, pool_v_l, tbl, pos, n_blk,
                        n_head, eps, block, trash, moe_top_k=2,
                        window=None, blk_lo=None, tp_axis=None,
                        tp_world=1, ep=None):
    """One layer's block-native decode step: x (1, 1, E) at position
    ``pos``, one layer's pool leaves ((N+1, H_kv, B, D) dense or
    (values, scales)), ``tbl`` the slot's trash-padded block table.
    Returns (x, kb, vb) where kb/vb are the UPDATED block containing
    ``pos`` — a read-modify-write of one pool block (this step's K/V
    row inserted at pos % block, every other lane a byte copy), which
    is what the caller scatters back.  The attention itself never
    materializes a row: O(block_size) workspace, ``n_blk`` loop
    iterations (trash / beyond-``pos`` lanes masked)."""
    quant = isinstance(pool_k_l, tuple)
    _, _, e = x.shape
    d = e // n_head        # full head dim: x is replicated under TP
    q, k_new, v_new = _paged_qkv(x, p, n_head, eps)
    if quant:
        k_cur, v_cur = _quantize_kv(k_new), _quantize_kv(v_new)
    else:
        k_cur, v_cur = k_new, v_new
    a = _paged_attn(q, pool_k_l, pool_v_l, tbl, pos, n_blk, block,
                    trash, k_cur, v_cur,
                    jnp.ones((1, 1), bool), 1.0 / math.sqrt(d),
                    window=window, blk_lo=blk_lo)
    a = a.astype(x.dtype).transpose(2, 0, 1, 3).reshape(
        1, 1, e // tp_world)
    x = x + (_tp_psum(a @ p["wo"], tp_axis, tp_world) + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + _mlp(h, p, moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
                 ep=ep)
    off = pos % block
    cur = tbl[pos // block]

    def rmw(pool_l, new):
        b = pool_l[cur]
        start = (0, off) + (0,) * (b.ndim - 2)
        return jax.lax.dynamic_update_slice(b, new, start)

    if quant:
        kb = (rmw(pool_k_l[0], k_cur[0]), rmw(pool_k_l[1], k_cur[1]))
        vb = (rmw(pool_v_l[0], v_cur[0]), rmw(pool_v_l[1], v_cur[1]))
    else:
        kb, vb = rmw(pool_k_l, k_cur), rmw(pool_v_l, v_cur)
    return x, kb, vb


def _block_chunk_paged(x, p, pool_k_l, pool_v_l, tbl, pos, n_blk,
                       n_head, eps, block, trash, moe_top_k=2,
                       window=None, blk_lo=None, tp_axis=None,
                       tp_world=1, ep=None):
    """The chunk-query variant (speculative verify): x (1, K, E) at
    positions ``pos..pos+K-1``.  Pool lanes < ``pos`` are visible to
    every query; the chunk's own keys are causal within the chunk —
    the same mask structure _block_chunk applies to its materialized
    row.  Returns (x, kdbl, vdbl): the DOUBLE block (blocks pos // B
    and (pos+K-1) // B concatenated on the position axis, K <= B so a
    chunk spans at most two) with the chunk's K/V rows inserted at
    pos % B — the caller splits and scatters the halves."""
    quant = isinstance(pool_k_l, tuple)
    _, klen, e = x.shape
    d = e // n_head
    q, k_new, v_new = _paged_qkv(x, p, n_head, eps)
    if quant:
        k_cur, v_cur = _quantize_kv(k_new), _quantize_kv(v_new)
    else:
        k_cur, v_cur = k_new, v_new
    cur_mask = jnp.tril(jnp.ones((klen, klen), bool))
    if window is not None:
        # within-chunk banding: query i attends chunk key j at
        # position pos+j only when (pos+i) - (pos+j) < window
        i = jnp.arange(klen)
        cur_mask = cur_mask & (i[:, None] - i[None, :] < window)
    a = _paged_attn(q, pool_k_l, pool_v_l, tbl, pos, n_blk, block,
                    trash, k_cur, v_cur, cur_mask,
                    1.0 / math.sqrt(d), window=window, blk_lo=blk_lo)
    a = a.astype(x.dtype).transpose(2, 0, 1, 3).reshape(
        1, klen, e // tp_world)
    x = x + (_tp_psum(a @ p["wo"], tp_axis, tp_world) + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + _mlp(h, p, moe_top_k, tp_axis=tp_axis, tp_world=tp_world,
                 ep=ep)
    b0 = pos // block
    b1 = (pos + klen - 1) // block
    off = pos % block

    def rmw2(pool_l, new):
        dd = jnp.concatenate([pool_l[tbl[b0]], pool_l[tbl[b1]]],
                             axis=1)
        start = (0, off) + (0,) * (dd.ndim - 2)
        return jax.lax.dynamic_update_slice(dd, new, start)

    if quant:
        kdbl = (rmw2(pool_k_l[0], k_cur[0]),
                rmw2(pool_k_l[1], k_cur[1]))
        vdbl = (rmw2(pool_v_l[0], v_cur[0]),
                rmw2(pool_v_l[1], v_cur[1]))
    else:
        kdbl, vdbl = rmw2(pool_k_l, k_cur), rmw2(pool_v_l, v_cur)
    return x, kdbl, vdbl


def decode_step_paged(params, x, pool_k, pool_v, tbl, pos, n_blk,
                      n_head, eps, *, block, trash, moe_top_k=2,
                      window=None, blk_lo=None, tp_axis=None,
                      tp_world=1, ep=None):
    """PUBLIC block-native single-step decode (the paged serve
    engine's hot path; serve/paged.py ``_paged_decode_kernel``).
    ``x``: (1, 1, E) embedded input at ``pos``; ``pool_k/v``: the full
    (L, N+1, H_kv, B, D) pools (int8 pools are (values, scales));
    ``tbl``: (W//B,) trash-padded block table; ``n_blk``: loop bound —
    any traced value >= ceil(pos / block) (the pool-step wrapper
    passes the max over live slots so one executable serves the whole
    pool).  Returns ((1, V) logits, kb, vb) with kb/vb the updated
    (L, H_kv, B, D)-stacked blocks containing ``pos``."""
    kbs, vbs = [], []
    for li, p in enumerate(params["blocks"]):
        x, kb, vb = _block_decode_paged(
            x, p, _cache_layer(pool_k, li), _cache_layer(pool_v, li),
            tbl, pos, n_blk, n_head, eps, block, trash,
            moe_top_k=moe_top_k, window=window, blk_lo=blk_lo,
            tp_axis=tp_axis, tp_world=tp_world, ep=ep)
        kbs.append(kb)
        vbs.append(vb)
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return _logits(x, params)[:, 0], _cache_stack(kbs), \
        _cache_stack(vbs)


def chunk_step_paged(params, x, pool_k, pool_v, tbl, pos, n_blk,
                     n_head, eps, *, block, trash, moe_top_k=2,
                     window=None, blk_lo=None, tp_axis=None,
                     tp_world=1, ep=None):
    """PUBLIC block-native chunk advance (speculative verify against
    the pool; serve/paged.py ``_paged_spec_kernel``).  ``x``:
    (1, K, E) embedded chunk at ``pos..pos+K-1``.  Returns
    ((1, K, V) logits, kdbl, vdbl) with the double blocks
    (L, H_kv, 2B, D)-stacked — the caller splits the halves and
    scatters them at ``tbl[pos // B]`` / ``tbl[(pos+K-1) // B]``."""
    kds, vds = [], []
    for li, p in enumerate(params["blocks"]):
        x, kd, vd = _block_chunk_paged(
            x, p, _cache_layer(pool_k, li), _cache_layer(pool_v, li),
            tbl, pos, n_blk, n_head, eps, block, trash,
            moe_top_k=moe_top_k, window=window, blk_lo=blk_lo,
            tp_axis=tp_axis, tp_world=tp_world, ep=ep)
        kds.append(kd)
        vds.append(vd)
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return _logits(x, params), _cache_stack(kds), _cache_stack(vds)


def spec_verify(t_logits, d_probs, props, key, temp, top_p, top_k,
                use_top_p):
    """Rejection-sampling chunk verify — the sampled half of
    speculative decoding (VERDICT missing #4), batched over the chunk's
    positions (vmap over slots batches it over rows; the serve engine's
    ``_pool_spec_step`` does exactly that).

    ``t_logits``: (spec_k, V) target logits at positions
    pos..pos+spec_k-1; ``d_probs``: (spec_k-1, V) post-filter draft
    distributions the proposals were drawn from; ``props``:
    (spec_k-1,) proposed tokens; ``temp`` is TRACED (a serve pool mixes
    greedy and sampled requests in one executable).  Returns
    ``(out (spec_k,) int32, a_draft int32)``: ``out[:a_draft]`` echo
    the accepted proposals, ``out[a_draft]`` is the correction token
    (residual resample, or the bonus draw on a full accept), entries
    past that are garbage the caller must not emit.  Tokens emitted =
    ``a_draft + 1``.

    Greedy (``temp <= 0``): accept while ``props[i] ==
    argmax(t_logits[i])``, emit the target's argmax at the stop
    position — the deterministic limit of the scheme and byte-identical
    to sequential target-greedy decode (up to chunk-vs-sequential
    einsum-order near-ties, same caveat as ``generate_speculative``).

    Sampled: position i's proposal is accepted with probability
    ``min(1, p_i(x) / q_i(x))`` where p/q are the POST-FILTER
    (temperature → top-k → top-p, via the shared ``_filter_logits``)
    target/draft distributions; the first rejection resamples from the
    normalized residual ``max(0, p_i − q_i)`` and stops; all spec_k−1
    accepted samples the bonus token from the last position's target
    distribution (expressed below as the residual against a virtual
    all-zero q row).  Marginally each emitted token is distributed
    EXACTLY as direct target sampling — the standard speculative
    sampling guarantee (Leviathan et al. / Chen et al. 2023) —
    pinned distributionally by tests/test_spec_serve.py's χ² gate.
    ``p == q`` makes the residual mass exactly 0; that degenerate case
    falls back to sampling from p (acceptance was certain anyway, any
    correction distribution is unreachable in exact arithmetic and p
    is the safe float-noise answer)."""
    spec_k, V = t_logits.shape
    t_logits = t_logits.astype(jnp.float32)
    # greedy branch: match-against-argmax, emit the target candidates
    cands = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
    match_g = props == cands[:-1]
    a_greedy = jnp.argmin(jnp.concatenate(
        [match_g, jnp.zeros((1,), bool)]))
    # sampled branch: post-filter target distributions per position
    ts = jnp.maximum(temp, 1e-6)
    p = jax.nn.softmax(jax.vmap(
        lambda lg: _filter_logits(lg, ts, top_p, top_k, use_top_p))(
            t_logits), axis=-1)                          # (spec_k, V)
    # virtual zero-q last row: its residual max(p-0, 0) IS the last
    # position's target distribution, so one gather serves both the
    # mid-chunk rejection resample and the full-accept bonus draw
    q = jnp.concatenate(
        [d_probs.astype(jnp.float32), jnp.zeros((1, V), jnp.float32)])
    k_acc, k_fix = jax.random.split(key)
    u = jax.random.uniform(k_acc, (spec_k - 1,))
    p_prop = jnp.take_along_axis(p[:-1], props[:, None], axis=-1)[:, 0]
    q_prop = jnp.take_along_axis(d_probs.astype(jnp.float32),
                                 props[:, None], axis=-1)[:, 0]
    # u < p/q without the division: q == 0 accepts iff p > 0 (the
    # ratio's limit), and p >= q accepts always (u < 1 <= p/q)
    accept = u * q_prop < p_prop
    a_sampled = jnp.argmin(jnp.concatenate(
        [accept, jnp.zeros((1,), bool)]))
    res = jnp.maximum(p[a_sampled] - q[a_sampled], 0.0)
    mass = jnp.sum(res)
    res = jnp.where(mass > 0.0, res / jnp.maximum(mass, 1e-38),
                    p[a_sampled])
    fix = jax.random.categorical(
        k_fix, jnp.log(jnp.maximum(res, 1e-38))).astype(jnp.int32)
    out_s = jnp.concatenate([props, jnp.zeros((1,), jnp.int32)])
    out_s = out_s.at[a_sampled].set(fix)
    greedy = temp <= 0.0
    out = jnp.where(greedy, cands, out_s)
    a_draft = jnp.where(greedy, a_greedy, a_sampled)
    return out, a_draft.astype(jnp.int32)


def _filter_logits(logit, temperature, top_p, top_k, use_top_p):
    """Temperature + top-k + top-p (nucleus) filtered f32 logits —
    exactly the tensor ``_sample(greedy=False)`` hands to
    ``jax.random.categorical``, factored out so the speculative
    rejection-sampling verify (:func:`spec_verify`) scores the SAME
    post-filter distribution the direct sampler draws from (any drift
    here is a silent distribution bug, so the code exists once)."""
    logit = logit.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(logit, top_k)[0][-1]
        logit = jnp.where(logit < kth, NEG_INF, logit)
    if use_top_p:
        order = jnp.argsort(-logit)
        sp = jax.nn.softmax(logit[order])
        cum = jnp.cumsum(sp)
        # smallest prefix with mass >= top_p: drop tokens whose
        # *preceding* cumulative mass already reached it (the top-1
        # token is always kept)
        keep_sorted = (cum - sp) < top_p
        keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
        logit = jnp.where(keep, logit, NEG_INF)
    return logit


def _sample(logit, key, temperature, top_p, greedy, top_k, use_top_p,
            min_p=1.0, use_min_p=False, rep_mask=None, rep_penalty=1.0,
            mask=None):
    """One token from a (V,) logit row.  ``greedy``/``top_k``/
    ``use_top_p``/``use_min_p`` are static; ``temperature``/``top_p``/
    ``min_p``/``rep_penalty`` are traced.  Filter order follows the
    de-facto standard (HF generate): repetition penalty (a processor —
    applies before greedy argmax too) → temperature → top-k → top-p
    (nucleus) → min-p → categorical.

    ``rep_mask`` (V,) bool marks tokens already in the sequence
    (prompt + emitted); their logits are divided by ``rep_penalty``
    when positive and multiplied when negative (CTRL semantics, as in
    HF).

    ``mask`` (V,) bool is the CONSTRAINED-decoding vocab mask (the
    serve engine's grammar automaton, serve/structured.py): False
    lanes drop to NEG_INF before greedy argmax AND before the filter
    chain, so both modes sample only grammar-legal tokens.  None (the
    default) and an all-True mask are bitwise no-ops — unconstrained
    streams cannot drift."""
    logit = logit.astype(jnp.float32)
    if mask is not None:
        logit = jnp.where(mask, logit, NEG_INF)
    if rep_mask is not None:
        pen = jnp.where(logit > 0, logit / rep_penalty,
                        logit * rep_penalty)
        logit = jnp.where(rep_mask, pen, logit)
    if greedy:
        return jnp.argmax(logit).astype(jnp.int32)
    logit = _filter_logits(logit, temperature, top_p, top_k, use_top_p)
    if use_min_p:
        # keep p >= min_p·p_max  ⇔  logit >= max + ln(min_p)
        logit = jnp.where(logit < jnp.max(logit) + jnp.log(min_p),
                          NEG_INF, logit)
    return jax.random.categorical(key, logit).astype(jnp.int32)


def _rep_mask_init(ids, live, vocab):
    """(ctx,) ids + (ctx,) live mask -> (V,) bool presence mask."""
    return jnp.zeros((vocab,), bool).at[ids].max(live)


def _generate_row(params, ids, prompt_len, key, temperature, top_p, *,
                  n_head, eps, n_new, greedy, top_k, use_top_p,
                  moe_top_k=2, unroll=4, quant_cache=False,
                  min_p=1.0, use_min_p=False, rep_penalty=1.0,
                  use_rep=False, window=None):
    """Single-prompt core: ids (ctx,) right-padded, returns (n_new,).
    Batched decoding vmaps this over (ids, prompt_len, key) — the
    per-row cache writes at differing positions lower to scatters.
    With ``use_rep`` a (V,) presence mask (prompt tokens + everything
    emitted) rides the scan carry for the repetition penalty."""
    hidden, kc, vc = prefill(params, ids[None, :], n_head, eps,
                             moe_top_k=moe_top_k, quant_cache=quant_cache,
                             window=window, prompt_end=prompt_len)
    # dense caches span ctx (prefill processed the full padded row);
    # windowed models return an O(window) ROLLING cache instead.
    # Vocab-project ONLY the last live row — (1, V), not (ctx, V)
    last_h = jax.lax.dynamic_index_in_dim(
        hidden, prompt_len - 1, axis=1, keepdims=False)    # (1, E)
    first_logit = _logits(last_h[:, None, :], params)[0, 0]  # (V,)

    def sample(logit, k, rep):
        return _sample(logit, k, temperature, top_p, greedy, top_k,
                       use_top_p, min_p=min_p, use_min_p=use_min_p,
                       rep_mask=rep, rep_penalty=rep_penalty)

    rep = None
    if use_rep:
        vocab = params["wte"].shape[0]
        rep = _rep_mask_init(ids, jnp.arange(ids.shape[0]) < prompt_len,
                             vocab)
    k0, key = jax.random.split(key)
    tok0 = sample(first_logit, k0, rep)
    if rep is not None:
        rep = rep.at[tok0].set(True)

    # ``rep`` rides the carry as None (an empty pytree leaf) when the
    # penalty is off — one scan body serves both modes
    def step(carry, _):
        tok, pos, kc, vc, key, rep = carry
        x = params["wte"][tok][None, None, :] + \
            params["wpe"][pos][None, None, :]
        logits, kc, vc = _advance_one(params, x, kc, vc, pos, n_head,
                                      eps, moe_top_k=moe_top_k,
                                      window=window)
        k, key = jax.random.split(key)
        nxt = sample(logits[0], k, rep)
        new_rep = None if rep is None else rep.at[nxt].set(True)
        return (nxt, pos + 1, kc, vc, key, new_rep), tok

    (last, *_), toks = jax.lax.scan(
        step, (tok0, prompt_len, kc, vc, key, rep), None,
        length=n_new - 1, unroll=min(unroll, max(1, n_new - 1)))
    return jnp.concatenate([toks, last[None]])


@partial(jax.jit, static_argnames=("n_head", "eps", "n_new", "ctx",
                                   "greedy", "top_k", "use_top_p",
                                   "moe_top_k", "unroll", "quant_cache",
                                   "use_min_p", "use_rep", "window"))
def generate_cached(params, ids, prompt_lens, n_head, eps, n_new, ctx,
                    greedy, temperature, keys, top_k=0, top_p=1.0,
                    use_top_p=False, moe_top_k=2, unroll=4,
                    quant_cache=False, min_p=1.0, use_min_p=False,
                    rep_penalty=1.0, use_rep=False, window=None):
    """One compiled prefill + lax.scan decode for a BATCH of prompts.
    ids: (B, ctx) right-padded; prompt_lens: (B,) int32; keys: (B, 2)
    PRNG keys.  Returns (B, n_new) sampled token ids.  ``top_k=0``
    disables top-k; ``use_top_p`` gates nucleus sampling (static so the
    sort compiles away when off).

    This is the per-row SCATTER path (vmapped row core, per-row
    positions, cache writes lower to scatters).  Since round 5 it is
    the EQUALITY ORACLE only: ``generate`` routes every batch — ragged
    included, via left-padding — through
    :func:`generate_cached_uniform`, whose shared position means one
    batched cache write and full-batch GEMMs per step (measured +66%
    tokens/sec at the bench config).  Kept because its math is
    transparently per-row right-padded, which is what the left-padded
    fast path must match token-for-token in f32
    (tests/test_gpt2.py)."""
    row = partial(_generate_row, n_head=n_head, eps=eps, n_new=n_new,
                  greedy=greedy, top_k=top_k, use_top_p=use_top_p,
                  moe_top_k=moe_top_k, unroll=unroll,
                  quant_cache=quant_cache, min_p=min_p,
                  use_min_p=use_min_p, rep_penalty=rep_penalty,
                  use_rep=use_rep, window=window)
    return jax.vmap(
        lambda i, n, k: row(params, i, n, k, temperature, top_p))(
            ids, prompt_lens, keys)


@partial(jax.jit, static_argnames=("n_head", "eps", "n_new", "ctx",
                                   "greedy", "top_k", "use_top_p",
                                   "moe_top_k", "unroll", "quant_cache",
                                   "use_min_p", "use_rep", "window"))
def generate_cached_uniform(params, ids, prompt_len, n_head, eps, n_new,
                            ctx, greedy, temperature, keys, top_k=0,
                            top_p=1.0, use_top_p=False, start=None,
                            moe_top_k=2, unroll=4, quant_cache=False,
                            min_p=1.0, use_min_p=False, rep_penalty=1.0,
                            use_rep=False, window=None):
    """Shared-position fast path: ids (B, ctx), ONE traced scalar
    ``prompt_len`` (the shared first free window position) — the
    per-step cache update is a single batched dynamic_update_slice and
    the projections run as full-batch GEMMs (the vmapped ragged path
    pays per-row scatters and B=1 matmuls for the same work).

    Equal-length batches: right-padded ids, ``start=None``.  RAGGED
    batches (round 5): LEFT-pad so every prompt ENDS at ``prompt_len``
    and pass ``start`` (B,) = the per-row first live position; the only
    per-row work is a wpe gather and the mask's lower bound — cache
    writes and GEMMs stay batched.  Token-exact vs the per-row scatter
    path in f32 (the oracle test); bf16 may flip argmax near-ties."""
    hidden, kc, vc = prefill(params, ids, n_head, eps, start=start,
                             moe_top_k=moe_top_k, quant_cache=quant_cache,
                             window=window, prompt_end=prompt_len)
    last_h = jax.lax.dynamic_index_in_dim(
        hidden, prompt_len - 1, axis=1, keepdims=False)     # (B, E)
    logits0 = _logits(last_h[:, None, :], params)[:, 0]     # (B, V)

    def sample(logits, keys_, rep):
        return jax.vmap(
            lambda lg, k, r: _sample(lg, k, temperature, top_p, greedy,
                                     top_k, use_top_p, min_p=min_p,
                                     use_min_p=use_min_p, rep_mask=r,
                                     rep_penalty=rep_penalty),
            in_axes=(0, 0, None if rep is None else 0))(
                logits, keys_, rep)

    rep = None
    if use_rep:
        vocab = params["wte"].shape[0]
        bsz = ids.shape[0]
        span = jnp.arange(ctx)[None, :]
        live = span < prompt_len
        if start is not None:  # left-padded: pads sit BEFORE start_i
            live = live & (span >= start[:, None])
        else:
            live = jnp.broadcast_to(live, (bsz, ctx))
        rep = jax.vmap(_rep_mask_init, in_axes=(0, 0, None))(
            ids, live, vocab)
    keys0 = jax.vmap(lambda k: jax.random.split(k))(keys)
    tok0 = sample(logits0, keys0[:, 0], rep)
    keys_cur = keys0[:, 1]
    if rep is not None:
        rep = rep.at[jnp.arange(ids.shape[0]), tok0].set(True)

    # ``rep`` rides the carry as None (an empty pytree leaf) when the
    # penalty is off — one scan body serves both modes
    def step(carry, t):
        toks, kc, vc, keys_cur, rep = carry
        pos = prompt_len + t
        if start is None:
            pe = params["wpe"][pos][None, None, :]
        else:
            # row-relative position: window pos − start_i
            pe = jnp.take(params["wpe"], pos - start, axis=0)[:, None, :]
        x = jnp.take(params["wte"], toks, axis=0)[:, None, :] + pe
        logits, kc, vc = _advance_one(params, x, kc, vc, pos, n_head,
                                      eps, start=start,
                                      moe_top_k=moe_top_k,
                                      window=window)
        ks = jax.vmap(lambda k: jax.random.split(k))(keys_cur)
        nxt = sample(logits, ks[:, 0], rep)
        new_rep = (None if rep is None
                   else rep.at[jnp.arange(nxt.shape[0]), nxt].set(True))
        return (nxt, kc, vc, ks[:, 1], new_rep), toks

    (last, *_), toks = jax.lax.scan(
        step, (tok0, kc, vc, keys_cur, rep), jnp.arange(n_new - 1),
        unroll=min(unroll, max(1, n_new - 1)))
    return jnp.concatenate([toks.T, last[:, None]], axis=1)


@partial(jax.jit, static_argnames=("n_head", "eps", "n_new", "ctx",
                                   "num_beams", "moe_top_k", "unroll",
                                   "quant_cache", "window"))
def _beam_search_cached(params, ids, prompt_len, n_head, eps, n_new,
                        ctx, num_beams, moe_top_k=2, start=None,
                        unroll=4, quant_cache=False, window=None):
    """Fixed-length beam search, ONE compiled prefill + scan, for a
    BATCH of prompts (round 5).  ids: (B, ctx) sharing one end
    position ``prompt_len`` (right-padded when equal-length; ragged
    batches come in LEFT-padded with ``start`` (B,) as in
    generate_cached_uniform).  Returns ((B, num_beams, n_new) token
    ids, (B, num_beams) total log-probs), best beam first per prompt.
    The beams are the batch — (B·K) rows advance lockstep, and each
    step reorders every prompt's K cache rows by parent with one
    BLOCK-DIAGONAL gather (global row index b·K + parent).  Exact when
    num_beams covers the frontier (tests compare against exhaustive
    search on tiny models, and batched-vs-looped equality)."""
    bsz = ids.shape[0]
    K = num_beams
    hidden, kc, vc = prefill(params, ids, n_head, eps, start=start,
                             moe_top_k=moe_top_k, quant_cache=quant_cache,
                             window=window, prompt_end=prompt_len)
    last_h = jax.lax.dynamic_index_in_dim(
        hidden, prompt_len - 1, axis=1, keepdims=False)      # (B, E)
    logp0 = jax.nn.log_softmax(
        _logits(last_h[:, None, :], params)[:, 0].astype(jnp.float32))
    V = logp0.shape[-1]
    k0 = min(K, V)
    top0, tok0 = jax.lax.top_k(logp0, k0)                    # (B, k0)
    # pad the beam set if num_beams > V (dead beams at -inf)
    pad = K - k0
    scores = jnp.concatenate(
        [top0, jnp.full((bsz, pad), NEG_INF, jnp.float32)], axis=1)
    toks = jnp.concatenate(
        [tok0, jnp.zeros((bsz, pad), jnp.int32)], axis=1)    # (B, K)
    # replicate the prompt caches across beams: (L, B, ...) ->
    # (L, B*K, ...) in (b, k) row-major order (tree-mapped: int8
    # caches are (values, scales) tuples)
    kc = jax.tree.map(lambda a: jnp.repeat(a, K, axis=1), kc)
    vc = jax.tree.map(lambda a: jnp.repeat(a, K, axis=1), vc)
    start_rows = None if start is None else jnp.repeat(start, K)
    seqs = jnp.zeros((bsz, K, n_new), jnp.int32)
    seqs = seqs.at[:, :, 0].set(toks)

    def step(carry, t):
        seqs, scores, toks, kc, vc = carry
        pos = prompt_len + t
        if start_rows is None:
            pe = params["wpe"][pos][None, None, :]
        else:
            pe = jnp.take(params["wpe"], pos - start_rows,
                          axis=0)[:, None, :]
        x = jnp.take(params["wte"], toks.reshape(-1),
                     axis=0)[:, None, :] + pe
        logits, kc, vc = _advance_one(params, x, kc, vc, pos, n_head,
                                      eps, start=start_rows,
                                      moe_top_k=moe_top_k,
                                      window=window)
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32)).reshape(bsz, K, V)
        cand = scores[:, :, None] + logp                 # (B, K, V)
        flat_scores, flat_idx = jax.lax.top_k(
            cand.reshape(bsz, K * V), K)                 # (B, K)
        parents = flat_idx // V                          # (B, K) in [0,K)
        toks = (flat_idx % V).astype(jnp.int32)
        seqs = jnp.take_along_axis(seqs, parents[:, :, None], axis=1)
        seqs = seqs.at[:, :, t + 1].set(toks)
        # block-diagonal cache reorder: beam rows only ever gather from
        # their own prompt's block
        glob = (jnp.arange(bsz)[:, None] * K + parents).reshape(-1)
        kc = jax.tree.map(lambda a: a[:, glob], kc)
        vc = jax.tree.map(lambda a: a[:, glob], vc)
        return (seqs, flat_scores, toks, kc, vc), None

    if n_new > 1:
        (seqs, scores, *_), _ = jax.lax.scan(
            step, (seqs, scores, toks, kc, vc),
            jnp.arange(n_new - 1), unroll=min(unroll, n_new - 1))
    # already best-first: top_k (and the padded init) sort descending
    return seqs, scores


def _is_batch(prompt_ids):
    """Shared batch-vs-single classification (a list of rows or a 2-D
    array is a batch; ragged batches defeat np.ndim on the whole
    object, so classify by the first element)."""
    if isinstance(prompt_ids, np.ndarray):
        return prompt_ids.ndim > 1
    seq = list(prompt_ids)
    return bool(seq) and np.ndim(seq[0]) > 0


def _normalize_prompts(prompt_ids, max_new_tokens, cfg,
                       over_length_hint=""):
    """Shared prompt handling for generate/generate_beam: classify
    single-vs-batch, coerce rows, length-check, and build the
    LEFT-padded shared-end window.  Returns (single, rows, lens,
    max_len, window, start) — ``start`` is None for equal-length
    batches (every row already ends at max_len = its length)."""
    single = not _is_batch(prompt_ids)
    seq = [prompt_ids] if single else list(prompt_ids)
    rows = [np.asarray(r, np.int32).reshape(-1) for r in seq]
    for r in rows:
        if len(r) + max_new_tokens > cfg.n_positions:
            raise ValueError(
                f"prompt ({len(r)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds n_positions ({cfg.n_positions})"
                + over_length_hint)
    lens = np.asarray([len(r) for r in rows], np.int32)
    max_len = int(lens.max()) if len(rows) else 0
    padded = np.zeros((len(rows), cfg.n_positions), np.int32)
    for i, r in enumerate(rows):
        padded[i, max_len - len(r):max_len] = r
    uniform = len(set(lens.tolist())) <= 1
    start = None if uniform else jnp.asarray(max_len - lens)
    return single, rows, lens, max_len, padded, start


def generate_beam(m, prompt_ids, max_new_tokens=20, num_beams=4,
                  dtype=None, unroll=4, cache_dtype=None):
    """Fixed-length beam search for a (optionally plan-sharded, possibly
    MoE) GPT2LMHead: returns the highest-total-log-prob continuation of
    ``max_new_tokens`` tokens.  Takes one 1-D prompt (returns one
    array) or a list/2-D batch, possibly ragged (returns a list) —
    all (B·num_beams) rows advance in ONE compiled executable, each
    prompt's beams reordering through a block-diagonal parent gather
    (round 5); ragged batches ride the left-padding machinery.
    ``num_beams=1`` equals greedy decoding.  No EOS handling — this
    framework's models are tokenizer-free, so sequences are
    fixed-length and the length penalty cancels."""
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    cfg = m.cfg
    single, rows, lens, max_len, padded, start = _normalize_prompts(
        prompt_ids, max_new_tokens, cfg)
    if max_new_tokens <= 0:
        out = [r.copy() for r in rows]
        return out[0] if single else out
    params = extract_params(m, dtype=dtype)
    seqs, _scores = _beam_search_cached(
        params, jnp.asarray(padded), max_len, cfg.n_head,
        float(cfg.layer_norm_eps), int(max_new_tokens),
        cfg.n_positions, int(num_beams),
        moe_top_k=int(getattr(cfg, "moe_top_k", 2) or 2), start=start,
        unroll=int(unroll), quant_cache=_quant_flag(cache_dtype),
        window=_norm_window(cfg))
    seqs = np.asarray(seqs)
    out = [np.concatenate([r, seqs[i, 0]]).astype(np.int32)
           for i, r in enumerate(rows)]
    return out[0] if single else out


def _norm_window(cfg):
    """The decode-effective sliding window: None when the model has no
    window or the window covers the whole position space (a rolling
    cache would then be the dense cache with extra index math)."""
    w = getattr(cfg, "attn_window", None)
    if w is None or w >= cfg.n_positions:
        return None
    return int(w)


def _quant_flag(cache_dtype):
    """Map the user-facing ``cache_dtype`` to the static jit flag.
    Only None (cache in the compute dtype) and "int8" exist — dtype
    strings that would not change behavior are rejected rather than
    silently accepted."""
    if cache_dtype is None:
        return False
    if cache_dtype == "int8":
        return True
    raise ValueError(f"cache_dtype must be None or 'int8', "
                     f"got {cache_dtype!r}")


def _seed(temperature, rng):
    # rng=None must stay non-deterministic across calls like the
    # windowed sampler's np.random fallback; accept both RandomState
    # (.randint) and Generator (.integers); greedy decoding draws
    # nothing (the key is unused, and consuming the caller's rng would
    # perturb downstream reproducibility)
    if temperature <= 0:
        return 0
    if rng is None:
        return int(np.random.randint(0, 2 ** 31 - 1))
    if hasattr(rng, "integers"):
        return int(rng.integers(0, 2 ** 31 - 1))
    return int(rng.randint(0, 2 ** 31 - 1))


def generate(m, prompt_ids, max_new_tokens=20, temperature=1.0, rng=None,
             top_k=0, top_p=None, min_p=None, repetition_penalty=None,
             dtype=None, unroll=4, cache_dtype=None,
             _ragged_impl="left"):
    """KV-cached sampling for a GPT2LMHead (dense or MoE,
    optionally plan-sharded).  Requires
    prompt_len + max_new_tokens <= cfg.n_positions (the windowed
    fallback in models/gpt2.py handles longer generations).

    ``prompt_ids``: one 1-D prompt (returns a 1-D array) or a list/2-D
    batch of prompts, possibly ragged (returns a list of 1-D arrays —
    each its prompt + continuation; all rows decode lockstep in ONE
    compiled executable).  Ragged batches are LEFT-padded onto the
    shared-position fast path (round 5); ``_ragged_impl="scatter"``
    selects the per-row vmap oracle instead (tests).  ``top_k``
    (int > 0) / ``top_p`` (0 < p ≤ 1) filter the temperature-scaled
    distribution before sampling.  ``dtype=jnp.bfloat16`` runs
    inference in bf16 (≈2× steady-state throughput; see
    extract_params).  ``cache_dtype="int8"`` quantizes the KV cache
    (symmetric per-(token, head) scales over D) — ~2× less cache
    traffic on a cache-read-bound loop, at the cost of quantization
    noise in the attention scores (argmax near-ties can flip; sampled
    distributions shift by the score error).  ``unroll`` (default 4):
    decode-loop unroll factor — the measured throughput/compile-time
    knee; see the module docstring."""
    cfg = m.cfg
    single, rows, lens, max_len, padded, start = _normalize_prompts(
        prompt_ids, max_new_tokens, cfg,
        over_length_hint="; use the windowed GPT2LMHead.generate")
    if max_new_tokens <= 0:
        out = [r.copy() for r in rows]
        return out[0] if single else out
    if top_k and top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    # HF behavior: top_k larger than the vocab means "no filter" — an
    # unclamped value would die at trace time inside lax.top_k with an
    # obscure shape error (advisor r04)
    top_k = min(int(top_k or 0), cfg.vocab_size)
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if min_p is not None and not 0.0 < min_p <= 1.0:
        raise ValueError(f"min_p must be in (0, 1], got {min_p}")
    if repetition_penalty is not None and repetition_penalty <= 0.0:
        raise ValueError(f"repetition_penalty must be > 0, "
                         f"got {repetition_penalty}")
    use_rep = (repetition_penalty is not None
               and repetition_penalty != 1.0)
    params = extract_params(m, dtype=dtype)
    ctx = cfg.n_positions
    bsz = len(rows)
    uniform = start is None
    if not uniform and _ragged_impl == "scatter":
        # the oracle path wants RIGHT-padded rows
        padded = np.zeros((bsz, ctx), np.int32)
        for i, r in enumerate(rows):
            padded[i, :len(r)] = r
    keys = jax.random.split(
        jax.random.PRNGKey(_seed(temperature, rng)), bsz)
    common = dict(
        top_k=int(top_k or 0),
        top_p=jnp.float32(1.0 if top_p is None else top_p),
        use_top_p=top_p is not None,
        min_p=jnp.float32(1.0 if min_p is None else min_p),
        use_min_p=min_p is not None,
        rep_penalty=jnp.float32(1.0 if repetition_penalty is None
                                else repetition_penalty),
        use_rep=use_rep,
        moe_top_k=int(getattr(cfg, "moe_top_k", 2) or 2),
        unroll=int(unroll), quant_cache=_quant_flag(cache_dtype),
        window=_norm_window(cfg))
    sample_args = (cfg.n_head, float(cfg.layer_norm_eps),
                   int(max_new_tokens), ctx, temperature <= 0,
                   jnp.float32(max(temperature, 1e-6)), keys)
    if uniform:
        new = generate_cached_uniform(
            params, jnp.asarray(padded), max_len, *sample_args,
            **common)
    elif _ragged_impl == "left":
        new = generate_cached_uniform(
            params, jnp.asarray(padded), max_len, *sample_args,
            start=start, **common)
    elif _ragged_impl == "scatter":
        # per-row vmap oracle (see generate_cached docstring)
        new = generate_cached(
            params, jnp.asarray(padded), jnp.asarray(lens),
            *sample_args, **common)
    else:
        raise ValueError(f"unknown _ragged_impl {_ragged_impl!r}; "
                         "expected 'left' or 'scatter'")
    new = np.asarray(new)
    out = [np.concatenate([r, new[i]]).astype(np.int32)
           for i, r in enumerate(rows)]
    return out[0] if single else out


# ---------------------------------------------------------------------------
# speculative decoding (greedy draft-and-verify, round 5)
# ---------------------------------------------------------------------------

def _spec_row(t_params, d_params, ids, prompt_len, spec_k,
              n_new, t_static, d_static, quant_cache=False):
    """Greedy speculative decoding ROW CORE (ids: (1, ctx)).

    Per chunk: the draft decodes ``spec_k - 1`` tokens sequentially
    (cheap model, cheap cache), then the target verifies the whole
    chunk with ONE chunked cache advance (_advance_chunk — one big
    cache read serves spec_k positions).  The emitted tokens are
    always the TARGET's greedy choices, so the output is exactly
    target-greedy whatever the draft proposes; the draft only decides
    how many positions each target read amortizes over.

    Cache rollback is FREE by design: both caches gate reads on
    position (live = slot <= pos), and every chunk's contiguous write
    at the new position overwrites any rows a rejected proposal left
    behind before they can ever become live again.

    ``t_static``/``d_static``: (n_head, eps, moe_top_k) per model.
    Returns (out tokens (n_new + spec_k,), n_chunks, n_accepted_draft)
    — acceptance rate = n_accepted_draft / (n_chunks * (spec_k - 1)).
    """
    tn, te, tm = t_static
    dn, de, dm = d_static
    t_hidden, t_kc, t_vc = prefill(t_params, ids, tn, te,
                                   moe_top_k=tm,
                                   quant_cache=quant_cache)
    _, d_kc, d_vc = prefill(d_params, ids, dn, de, moe_top_k=dm,
                            quant_cache=quant_cache)
    last_h = jax.lax.dynamic_index_in_dim(
        t_hidden, prompt_len - 1, axis=1, keepdims=False)
    first = jnp.argmax(
        _logits(last_h[:, None, :], t_params)[0, 0]).astype(jnp.int32)
    out = jnp.zeros((n_new + spec_k,), jnp.int32)
    out = out.at[0].set(first)

    def cond(c):
        return c[1] < n_new

    def body(c):
        out, n_emit, pos, last, t_kc, t_vc, d_kc, d_vc, chunks, acc = c

        def dstep(dc, _):
            d_kc, d_vc, tok, dpos = dc
            x = (d_params["wte"][tok] + d_params["wpe"][dpos])[None, None]
            lg, d_kc, d_vc = _advance_one(d_params, x, d_kc, d_vc,
                                          dpos, dn, de, moe_top_k=dm)
            nxt = jnp.argmax(lg[0]).astype(jnp.int32)
            return (d_kc, d_vc, nxt, dpos + 1), nxt

        # spec_k steps, spec_k - 1 proposals: the extra step processes
        # the LAST proposal as an input so the draft cache always has
        # a row for position pos + spec_k - 1 — without it, a
        # full-accept chunk (whose bonus advances past every draft
        # write) leaves the next chunk's draft reading a stale prefill
        # row (caught by the self-draft acceptance test: acceptance
        # was 0.83, not 1.0, on a trained model)
        (d_kc, d_vc, _, _), props = jax.lax.scan(
            dstep, (d_kc, d_vc, last, pos), None, length=spec_k)
        props = props[:-1]

        chunk_toks = jnp.concatenate([last[None], props])   # (spec_k,)
        xs = (jnp.take(t_params["wte"], chunk_toks, axis=0)
              + jnp.take(t_params["wpe"],
                         pos + jnp.arange(spec_k), axis=0))[None]
        lg, t_kc, t_vc = _advance_chunk(t_params, xs, t_kc, t_vc, pos,
                                        tn, te, moe_top_k=tm)
        cands = jnp.argmax(lg[0], axis=-1).astype(jnp.int32)  # c_1..c_k
        match = props == cands[:-1]
        # first mismatch index = number of ACCEPTED draft tokens; all
        # matched -> spec_k - 1 accepted + the bonus candidate
        a_draft = jnp.argmin(jnp.concatenate(
            [match, jnp.zeros((1,), bool)]))
        a = a_draft + 1                     # tokens emitted this chunk
        # write the whole candidate block at n_emit; entries beyond
        # ``a`` are overwritten by the next chunk before they can
        # count (same argument as the cache rows)
        out = jax.lax.dynamic_update_slice(out, cands, (n_emit,))
        last = cands[a_draft]
        return (out, n_emit + a, pos + a, last, t_kc, t_vc, d_kc,
                d_vc, chunks + 1, acc + a_draft)

    out, n_emit, pos, last, *_, chunks, acc = jax.lax.while_loop(
        cond, body,
        (out, jnp.int32(1), jnp.asarray(prompt_len, jnp.int32), first,
         t_kc, t_vc, d_kc, d_vc, jnp.int32(0), jnp.int32(0)))
    return out, chunks, acc


@partial(jax.jit, static_argnames=("spec_k", "n_new", "t_static",
                                   "d_static", "quant_cache"))
def _speculative_loop(t_params, d_params, ids, prompt_lens, spec_k,
                      n_new, t_static, d_static, quant_cache=False):
    """Batched speculative decoding: vmap of the row core over (B, ctx)
    right-padded prompts with per-row lengths.  Rows accept at
    different rates, so each runs its own chunk loop — JAX's
    while_loop batching executes until every row has emitted n_new
    tokens, freezing finished rows' carries (their discarded body
    re-executions index past their window; jax gathers clip, and the
    headroom check in generate_speculative keeps live rows in
    bounds).  Per-row caches mean per-row scatters, like the ragged
    scatter oracle — speculation is a latency device for SMALL
    batches, which is exactly where that cost is irrelevant.
    Returns ((B, n_new + spec_k) tokens, (B,) chunks, (B,)
    accepted).

    B == 1 (the primary latency case) dispatches the UNBATCHED row
    core: the batched while_loop rule rewrites every chunk as
    carry = select(done, carry, body(carry)) over the full K/V cache
    carries, an elementwise cache copy per chunk that a single prompt
    need not pay."""
    if ids.shape[0] == 1:
        out, chunks, acc = _spec_row(
            t_params, d_params, ids, prompt_lens[0], spec_k, n_new,
            t_static, d_static, quant_cache=quant_cache)
        return (out[None], jnp.asarray(chunks)[None],
                jnp.asarray(acc)[None])
    return jax.vmap(
        lambda row, n: _spec_row(t_params, d_params, row[None, :], n,
                                 spec_k, n_new, t_static, d_static,
                                 quant_cache=quant_cache))(
                                     ids, prompt_lens)


def generate_speculative(target, draft, prompt_ids, max_new_tokens=20,
                         spec_k=4, dtype=None, cache_dtype=None):
    """Greedy speculative decoding: ``draft`` (a smaller GPT2LMHead)
    proposes ``spec_k - 1`` tokens per chunk, ``target`` verifies the
    chunk in one cache read, and every emitted token is the TARGET's
    greedy choice — the draft only changes the speed.  Matches
    ``target.generate(prompt, temperature=0)`` token for token up to
    argmax near-ties: the chunked verify computes the same logits as
    sequential decode to ~1e-7 (einsum order), so only a model whose
    top-2 logits tie within that can flip (tested exact on trained
    models; with ``cache_dtype="int8"`` the comparison point is int8
    sequential decode).  Returns ``(ids, stats)`` where ids is
    prompt + continuation and stats carries ``acceptance_rate`` (the
    fraction of draft proposals the target kept; None when nothing
    was verified), ``chunks``, and ``tokens_per_chunk``.

    Speedup condition: decode is cache/weight-read-bound, so one
    verify read amortized over ``a`` accepted positions beats ``a``
    sequential target steps whenever the draft is cheap and agrees
    often (acceptance is a property of the MODEL PAIR and data, not
    of this mechanism).

    Speculation-vs-unroll crossover (when each pays): the sequential
    path already amortizes loop overhead with ``unroll=4`` (+76%
    measured, PERF.md §8), so speculation must beat the UNROLLED
    baseline, not the naive one.  Per emitted token the speculative
    loop costs ``spec_k · c_draft + c_verify(spec_k)`` per ``a``
    emitted tokens (``a = 1 + acceptance·(spec_k−1)`` expected), vs
    one unrolled target step; with a draft ``r×`` cheaper than the
    target and the chunk verify ≈ one target step on a
    cache-read-bound loop, speculation wins when
    ``(spec_k/r + 1) / a < 1`` — e.g. at ``spec_k=4``, ``r≈8``
    (the 1-vs-2-layer demo pair is ~2×; production drafts are
    8–20×), break-even sits near acceptance ≈ 0.17 and the measured
    3.92 tokens/chunk at acceptance ≈ 0.97 is a ~2.6× bound.  Low
    acceptance (< ~0.3 at spec_k=4) or an expensive draft (r < 2)
    means the unrolled sequential loop is the faster choice; raising
    spec_k helps only while acceptance stays high (expected emitted
    tokens saturate at ``1/(1−acceptance)``).  Measured points for
    this model: ``bench_serve.py --spec-sweep`` runs spec_k ∈
    {2, 4, 8} on a trained pair and commits tokens/s vs measured
    acceptance per k to BENCH_SERVE.json (the ``spec_sweep``
    section, ``chip_pending`` — CPU prices the k sequential draft
    steps differently from a chip, so the peak-k is ratified on
    hardware).  The serve engine
    exposes the same trade via ``model.serve(draft_model=,
    spec_k=)``, where per-engine ``serve.spec.{accepted,drafted}``
    metrics measure the realized acceptance on live traffic; sampled
    (temperature/top-p) speculation lives there too, via
    :func:`spec_verify` — this offline entry is greedy-only.

    Takes one 1-D prompt (returns one array) or
    a list/2-D batch, possibly ragged (returns a list): rows accept
    at different rates, so each runs its own vmapped chunk loop
    until every row finishes — per-row cache scatters like the
    ragged oracle path, which is irrelevant at the small batches
    speculation targets.  Greedy only; sliding-window models are not
    supported (the rolling cache's slot arithmetic does not admit
    the chunked overwrite-rollback trick)."""
    cfg_t, cfg_d = target.cfg, draft.cfg
    if cfg_t.vocab_size != cfg_d.vocab_size:
        raise ValueError(
            f"target/draft vocab mismatch: {cfg_t.vocab_size} vs "
            f"{cfg_d.vocab_size}")
    for name, cfg in (("target", cfg_t), ("draft", cfg_d)):
        if getattr(cfg, "attn_window", None) is not None:
            raise NotImplementedError(
                f"speculative decoding does not support sliding-window "
                f"models ({name} has attn_window={cfg.attn_window})")
    if spec_k < 2:
        raise ValueError(f"spec_k must be >= 2, got {spec_k}")
    single = not _is_batch(prompt_ids)
    rows = ([prompt_ids] if single else list(prompt_ids))
    rows = [np.asarray(r, np.int32).reshape(-1) for r in rows]
    ctx = min(cfg_t.n_positions, cfg_d.n_positions)
    # the verify chunk may run up to spec_k - 1 positions past the
    # last emitted token, so reserve that headroom in the window
    for r in rows:
        if len(r) + max_new_tokens + spec_k - 1 > ctx:
            raise ValueError(
                f"prompt ({len(r)}) + max_new_tokens "
                f"({max_new_tokens}) + spec_k-1 ({spec_k - 1}) exceeds "
                f"n_positions ({ctx})")
    if max_new_tokens <= 0:
        outs = [r.copy() for r in rows]
        stats = {"acceptance_rate": None, "chunks": 0,
                 "tokens_per_chunk": None,
                 "per_row_chunks": [0] * len(rows)}
        return (outs[0] if single else outs), stats
    t_params = extract_params(target, dtype=dtype)
    d_params = extract_params(draft, dtype=dtype)
    bsz = len(rows)
    ids = np.zeros((bsz, ctx), np.int32)
    for i, r in enumerate(rows):
        ids[i, :len(r)] = r
    lens = jnp.asarray([len(r) for r in rows], jnp.int32)
    out, chunks, acc = _speculative_loop(
        t_params, d_params, jnp.asarray(ids), lens,
        int(spec_k), int(max_new_tokens),
        (cfg_t.n_head, float(cfg_t.layer_norm_eps),
         int(getattr(cfg_t, "moe_top_k", 2) or 2)),
        (cfg_d.n_head, float(cfg_d.layer_norm_eps),
         int(getattr(cfg_d, "moe_top_k", 2) or 2)),
        quant_cache=_quant_flag(cache_dtype))
    out = np.asarray(out)
    chunks = np.asarray(chunks)
    acc = np.asarray(acc)
    total_chunks = int(chunks.sum())
    # chunks == 0 (max_new_tokens == 1: the prefill token was enough)
    # verified zero proposals — report None, not an arbitrary rate
    stats = {
        "acceptance_rate": (float(acc.sum())
                            / (total_chunks * (spec_k - 1))
                            if total_chunks else None),
        "chunks": total_chunks,
        "tokens_per_chunk": (bsz * (max_new_tokens - 1) / total_chunks
                             if total_chunks else None),
        "per_row_chunks": chunks.tolist(),
    }
    outs = [np.concatenate([r, out[i, :max_new_tokens]]).astype(np.int32)
            for i, r in enumerate(rows)]
    return (outs[0] if single else outs), stats
