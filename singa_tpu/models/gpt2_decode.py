"""KV-cached incremental decoding for GPT-2 (TPU-native inference path).

The reference has no inference machinery at all (its ONNX examples run
full forwards — SURVEY.md §2.4); the round-2 ``generate`` here did the
fixed-window equivalent: one FULL-context forward per emitted token,
O(S²·T) total attention work.  This module is the idiomatic TPU design:

* **prefill** — one causal forward over the (padded) prompt that also
  returns every layer's K/V, written into a preallocated
  ``(L, B, H, ctx, D)`` cache;
* **decode** — a single ``lax.scan`` over new tokens, each step
  attending its one-query block against the cache (masked to the live
  positions) and writing its K/V at the current position with
  ``lax.dynamic_update_slice`` — O(S·D) per token, static shapes, ONE
  compiled executable for the whole generation.

The math mirrors the layer stack exactly (same fp32-stat LayerNorm,
same tanh-approx gelu, same scale placement), and
``tests/test_gpt2.py`` asserts the cached step's logits equal the full
forward's to tolerance at every position.  Dense single-device models
only (no plan, no MoE) — sampling under a sharded plan still uses the
windowed path.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def extract_params(m):
    """Pull the dense GPT2LMHead weight pytree (raw jax arrays).
    Raises for MoE/plan variants — those sample via the windowed path."""
    t = m.transformer
    if m.plan is not None:
        raise ValueError("KV-cache decode is single-device (plan=None)")
    blocks = []
    for blk in t.blocks:
        mlp = blk.mlp
        if mlp is None:
            raise RuntimeError("model not initialized: call compile() or "
                               "run one forward first")
        if not hasattr(mlp, "fc1"):
            raise ValueError("KV-cache decode does not support MoE blocks")
        blocks.append(dict(
            ln1_s=blk.ln1.scale.data, ln1_b=blk.ln1.bias.data,
            wq=blk.attn.q_proj.W.data, bq=blk.attn.q_proj.b.data,
            wk=blk.attn.k_proj.W.data, bk=blk.attn.k_proj.b.data,
            wv=blk.attn.v_proj.W.data, bv=blk.attn.v_proj.b.data,
            wo=blk.attn.out_proj.W.data, bo=blk.attn.out_proj.b.data,
            ln2_s=blk.ln2.scale.data, ln2_b=blk.ln2.bias.data,
            w1=mlp.fc1.W.data, b1=mlp.fc1.b.data,
            w2=mlp.fc2.W.data, b2=mlp.fc2.b.data,
        ))
    head = None if m.cfg.tie_weights else m.lm_head.W.data
    return dict(wte=t.wte.W.data, wpe=t.wpe.W.data, blocks=blocks,
                lnf_s=t.ln_f.scale.data, lnf_b=t.ln_f.bias.data,
                head=head)


def _ln(x, s, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * s + b).astype(x.dtype)


def _attn_full(q, k, v, n_head):
    """Causal attention over the full (B, S, E) prefill block."""
    b, s, e = q.shape
    d = e // n_head

    def heads(t):
        return t.reshape(b, s, n_head, d).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q), heads(k), heads(v)
    sc = jnp.einsum("bhsd,bhtd->bhst", qh, kh) / math.sqrt(d)
    cm = jnp.tril(jnp.ones((s, s), bool))
    sc = jnp.where(cm[None, None], sc, NEG_INF)
    p = jax.nn.softmax(sc, axis=-1)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return o.transpose(0, 2, 1, 3).reshape(b, s, e)


def _block_prefill(x, p, n_head, eps):
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = h @ p["wq"] + p["bq"]
    k = h @ p["wk"] + p["bk"]
    v = h @ p["wv"] + p["bv"]
    a = _attn_full(q, k, v, n_head)
    x = x + (a @ p["wo"] + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + (jax.nn.gelu(h @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"])
    return x, k, v


def _block_decode(x, p, k_cache, v_cache, pos, n_head, eps):
    """x: (B, 1, E); k/v_cache: (B, H, ctx, D) with this step's K/V
    already written at ``pos``.  Attends to positions <= pos."""
    b, _, e = x.shape
    d = e // n_head
    ctx = k_cache.shape[2]
    h = _ln(x, p["ln1_s"], p["ln1_b"], eps)
    q = (h @ p["wq"] + p["bq"]).reshape(b, n_head, 1, d)
    k_new = (h @ p["wk"] + p["bk"]).reshape(b, n_head, 1, d)
    v_new = (h @ p["wv"] + p["bv"]).reshape(b, n_head, 1, d)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new, (0, 0, pos, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new, (0, 0, pos, 0))
    sc = jnp.einsum("bhqd,bhtd->bhqt", q, k_cache) / math.sqrt(d)
    live = jnp.arange(ctx)[None, None, None, :] <= pos
    sc = jnp.where(live, sc, NEG_INF)
    p_attn = jax.nn.softmax(sc, axis=-1)
    a = jnp.einsum("bhqt,bhtd->bhqd", p_attn, v_cache)
    a = a.transpose(0, 2, 1, 3).reshape(b, 1, e)
    x = x + (a @ p["wo"] + p["bo"])
    h = _ln(x, p["ln2_s"], p["ln2_b"], eps)
    x = x + (jax.nn.gelu(h @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"])
    return x, k_cache, v_cache


def _logits(x, params):
    head = params["head"]
    if head is None:
        return x @ params["wte"].T
    return x @ head


def prefill(params, ids, n_head, eps):
    """ids: (B, Sp) int32 (padded prompt).  Returns (hidden, k_caches,
    v_caches): hidden is the final-LN (B, Sp, E) — the caller picks the
    rows it needs BEFORE the vocab matmul (materializing (Sp, V) logits
    for all pad positions would double prefill cost) — and caches are
    (L, B, H, Sp, D); pad positions hold garbage K/V that decode never
    attends to (mask is position-indexed)."""
    b, sp = ids.shape
    pos = jnp.arange(sp, dtype=jnp.int32)[None, :]
    x = jnp.take(params["wte"], ids, axis=0) + \
        jnp.take(params["wpe"], pos, axis=0)
    ks, vs = [], []
    for p in params["blocks"]:
        x, k, v = _block_prefill(x, p, n_head, eps)
        e = x.shape[-1]
        d = e // n_head
        ks.append(k.reshape(b, sp, n_head, d).transpose(0, 2, 1, 3))
        vs.append(v.reshape(b, sp, n_head, d).transpose(0, 2, 1, 3))
    x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
    return x, jnp.stack(ks), jnp.stack(vs)


@partial(jax.jit, static_argnames=("n_head", "eps", "n_new", "ctx",
                                   "greedy"))
def generate_cached(params, ids, prompt_len, n_head, eps, n_new, ctx,
                    greedy, temperature, key):
    """One compiled prefill + lax.scan decode.  ids: (1, ctx) right-
    padded prompt; returns (1, n_new) sampled token ids."""
    hidden, kc, vc = prefill(params, ids, n_head, eps)
    # caches preallocated at ctx; prefill already spans ctx here.
    # Vocab-project ONLY the last live row — (1, V), not (ctx, V)
    last_h = jax.lax.dynamic_index_in_dim(
        hidden, prompt_len - 1, axis=1, keepdims=False)    # (1, E)
    first_logit = _logits(last_h[:, None, :], params)[0, 0]  # (V,)

    def sample(logit, k):
        if greedy:
            return jnp.argmax(logit).astype(jnp.int32)
        p = jax.nn.softmax(logit.astype(jnp.float32) / temperature)
        return jax.random.categorical(
            k, jnp.log(jnp.maximum(p, 1e-30))).astype(jnp.int32)

    k0, key = jax.random.split(key)
    tok0 = sample(first_logit, k0)

    def step(carry, _):
        tok, pos, kc, vc, key = carry
        x = params["wte"][tok][None, None, :] + \
            params["wpe"][pos][None, None, :]
        new_kc, new_vc = [], []
        for li, p in enumerate(params["blocks"]):
            x, kl, vl = _block_decode(x, p, kc[li], vc[li], pos, n_head,
                                      eps)
            new_kc.append(kl)
            new_vc.append(vl)
        kc = jnp.stack(new_kc)
        vc = jnp.stack(new_vc)
        x = _ln(x, params["lnf_s"], params["lnf_b"], eps)
        logit = _logits(x, params)[0, 0]
        k, key = jax.random.split(key)
        nxt = sample(logit, k)
        return (nxt, pos + 1, kc, vc, key), tok

    (last, _, _, _, _), toks = jax.lax.scan(
        step, (tok0, prompt_len, kc, vc, key), None, length=n_new - 1)
    return jnp.concatenate([toks, last[None]])[None, :]


def generate(m, prompt_ids, max_new_tokens=20, temperature=1.0, rng=None):
    """KV-cached sampling for a dense GPT2LMHead.  Requires
    prompt_len + max_new_tokens <= cfg.n_positions (the windowed
    fallback in models/gpt2.py handles longer generations)."""
    params = extract_params(m)
    cfg = m.cfg
    ids = np.asarray(prompt_ids, np.int32).reshape(-1)
    n0 = len(ids)
    if max_new_tokens <= 0:
        return ids.copy()
    if n0 + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt ({n0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"n_positions ({cfg.n_positions}); use the windowed "
            "GPT2LMHead.generate")
    ctx = cfg.n_positions
    window = np.zeros((1, ctx), np.int32)
    window[0, :n0] = ids
    # rng=None must stay non-deterministic across calls like the
    # windowed sampler's np.random fallback; accept both RandomState
    # (.randint) and Generator (.integers); greedy decoding draws
    # nothing (the key is unused, and consuming the caller's rng would
    # perturb downstream reproducibility)
    if temperature <= 0:
        seed = 0
    elif rng is None:
        seed = int(np.random.randint(0, 2 ** 31 - 1))
    elif hasattr(rng, "integers"):
        seed = int(rng.integers(0, 2 ** 31 - 1))
    else:
        seed = int(rng.randint(0, 2 ** 31 - 1))
    new = generate_cached(
        params, jnp.asarray(window), n0, cfg.n_head,
        float(cfg.layer_norm_eps), int(max_new_tokens), ctx,
        temperature <= 0, jnp.float32(max(temperature, 1e-6)),
        jax.random.PRNGKey(seed))
    return np.concatenate([ids, np.asarray(new[0])]).astype(np.int32)
