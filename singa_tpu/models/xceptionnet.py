"""Xception (reference: examples/cnn/model/xceptionnet.py, unverified —
depthwise-separable conv blocks).  Depthwise = grouped conv with
group == in_channels, which XLA lowers efficiently on TPU."""

from .. import layer
from .common import Classifier


class SeparableConv2d(layer.Layer):
    def __init__(self, out_channels, kernel_size, stride=1, padding=0):
        super().__init__()
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.depthwise = None
        self.pointwise = layer.Conv2d(out_channels, 1, bias=False)

    def initialize(self, x):
        in_channels = x.shape[1]
        self.depthwise = layer.Conv2d(
            in_channels, self.kernel_size, stride=self.stride,
            padding=self.padding, group=in_channels, bias=False)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class XceptionBlock(layer.Layer):
    """Reference Xception block: ``grow_first=True`` grows channels at the
    first separable conv, ``False`` at the last; the skip branch exists
    whenever channels or stride change.  Channel counts depend on the
    input, so construction happens in ``initialize``."""

    def __init__(self, out_filters, reps, stride=1, start_with_relu=True,
                 grow_first=True):
        super().__init__()
        self.stride = stride
        self.start_with_relu = start_with_relu
        self.grow_first = grow_first
        self.out_filters = out_filters
        self.reps = reps
        self.skip = None
        self.skipbn = None
        self.pool = layer.MaxPool2d(3, stride, padding=1) if stride != 1 else None
        self.add = layer.Add()

    def initialize(self, x):
        in_filters = x.shape[1]
        if self.stride != 1 or in_filters != self.out_filters:
            self.skip = layer.Conv2d(self.out_filters, 1, stride=self.stride,
                                     bias=False)
            self.skipbn = layer.BatchNorm2d()
        if self.grow_first:
            widths = [self.out_filters] * self.reps
        else:
            widths = [in_filters] * (self.reps - 1) + [self.out_filters]
        self.sepconvs = [SeparableConv2d(w, 3, 1, 1) for w in widths]
        self.bns = [layer.BatchNorm2d() for _ in range(self.reps)]
        self.relus = [layer.ReLU() for _ in range(self.reps)]

    def forward(self, x):
        y = x
        for i in range(self.reps):
            if i > 0 or self.start_with_relu:
                y = self.relus[i](y)
            y = self.sepconvs[i](y)
            y = self.bns[i](y)
        if self.pool is not None:
            y = self.pool(y)
        if self.skip is not None:
            skip = self.skipbn(self.skip(x))
        else:
            skip = x
        return self.add(y, skip)


class Xception(Classifier):
    def __init__(self, num_classes=1000, num_channels=3):
        super().__init__()
        self.num_classes = num_classes
        self.input_size = 299
        self.dimension = 4
        self.conv1 = layer.Conv2d(32, 3, stride=2, padding=0, bias=False)
        self.bn1 = layer.BatchNorm2d()
        self.relu1 = layer.ReLU()
        self.conv2 = layer.Conv2d(64, 3, padding=0, bias=False)
        self.bn2 = layer.BatchNorm2d()
        self.relu2 = layer.ReLU()

        self.block1 = XceptionBlock(128, 2, 2, start_with_relu=False)
        self.block2 = XceptionBlock(256, 2, 2)
        self.block3 = XceptionBlock(728, 2, 2)
        self.middle = [XceptionBlock(728, 3, 1) for _ in range(8)]
        self.block12 = XceptionBlock(1024, 2, 2, grow_first=False)

        self.conv3 = SeparableConv2d(1536, 3, 1, 1)
        self.bn3 = layer.BatchNorm2d()
        self.relu3 = layer.ReLU()
        self.conv4 = SeparableConv2d(2048, 3, 1, 1)
        self.bn4 = layer.BatchNorm2d()
        self.relu4 = layer.ReLU()
        self.globalpool = layer.GlobalAvgPool2d()
        self.fc = layer.Linear(num_classes)

    def forward(self, x):
        y = self.relu1(self.bn1(self.conv1(x)))
        y = self.relu2(self.bn2(self.conv2(y)))
        y = self.block1(y)
        y = self.block2(y)
        y = self.block3(y)
        for blk in self.middle:
            y = blk(y)
        y = self.block12(y)
        y = self.relu3(self.bn3(self.conv3(y)))
        y = self.relu4(self.bn4(self.conv4(y)))
        y = self.globalpool(y)
        return self.fc(y)


def create_model(**kw):
    return Xception(**kw)
