"""Model zoo mirroring the reference's ``examples/*/model/`` trees
(SURVEY.md §2.4): MLP, CNN, AlexNet, ResNet, VGG, MobileNetV2,
XceptionNet, char-RNN LSTM, BERT, GPT-2 (incl. a tensor/sequence/
expert-parallel GPT-MoE variant)."""
