"""Model zoo mirroring the reference's ``examples/*/model/`` trees
(SURVEY.md §2.4): MLP, CNN, AlexNet, ResNet, XceptionNet, char-RNN LSTM,
BERT."""
