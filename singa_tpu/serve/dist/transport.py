"""Framed socket transport for the distributed serve fleet.

One wire format for everything the fleet says to a worker peer:
length-prefixed, versioned, crc-checked frames over a plain TCP
socket.  Three message kinds ride it —

* ``CALL``/``REPLY`` — the synchronous control RPC the fleet drives a
  replica with (submit/step/build/export/...).  Every call carries a
  sequence number, a typed timeout, and an optional bounded
  retry-with-backoff for idempotent operations;
* ``ONEWAY`` — fire-and-forget messages that must not stall the
  sender: streamed KV ship frames (dist/fleet.py relays them to the
  destination while the source is still prefilling) and best-effort
  aborts/shutdowns;
* ``HELLO`` — the connect-time handshake: a worker proves it belongs
  to THIS fleet (shared token) and says which replica index it is.

Frame layout (all integers network byte order)::

    | magic 'STPU' | u8 proto | u8 kind | u32 crc32(payload) |
    | u64 length   | payload (pickle)                        |

A frame that fails the magic, version, crc, or length checks raises
:class:`TransportError` — the stream is unusable after that (framing
lost), so callers escalate to peer loss.  Socket-level failures map to
the PEER-LOSS family: :class:`PeerGoneError` subclasses
``RestartBudgetExceededError`` ON PURPOSE — to the fleet, a worker
that dropped off the network and a supervisor that spent its restart
budget are the same event ("this replica cannot serve; fail over"),
so every existing fleet path (admission, step, ship driving) handles a
partition with zero new code.  :class:`PeerTimeoutError` narrows it
for calls that exceeded their deadline after retries.

Heartbeats are PIGGYBACKED: every received frame refreshes the
connection's ``last_rx`` clock, so a busy peer never pays a separate
ping, and ``Conn.age()`` tells the fleet's watchdog how stale a quiet
peer is (it pings only those — serve/dist/fleet.py
``_check_watchdog``).

The ``serve.dist.rpc`` fault site is checked on the CALLER side of
every RPC (when armed): a fired fault is a modeled network partition —
the peer process is still alive, but this side treats it as gone,
which is exactly what a partition looks like from one end.
"""

from __future__ import annotations

import pickle
import socket
import struct
import time
import zlib

from ...resilience import faults as _faults
from ...utils.logging import get_channel
from ..request import RestartBudgetExceededError

__all__ = ["PROTO_VERSION", "TransportError", "PeerGoneError",
           "PeerTimeoutError", "Conn", "Listener", "MSG_CALL",
           "MSG_REPLY", "MSG_ONEWAY", "MSG_HELLO"]

#: bump when the frame layout or the RPC envelope changes; a peer on a
#: different proto version fails the handshake typed instead of
#: misparsing frames
PROTO_VERSION = 1

MSG_CALL = 1
MSG_REPLY = 2
MSG_ONEWAY = 3
MSG_HELLO = 4

_MAGIC = b"STPU"
_HEAD = struct.Struct("!4sBBIQ")
#: refuse absurd frame lengths before allocating: the largest honest
#: payload is a KV image of a test/bench pool (MBs); 1 GiB means a
#: corrupted length field, not a message
_MAX_FRAME = 1 << 30


class TransportError(RuntimeError):
    """The byte stream itself is broken: bad magic, proto-version
    skew, a crc mismatch, or a length-lying frame.  Framing is lost
    after this — the connection cannot be trusted for another
    message, so callers escalate to peer loss."""


class PeerGoneError(RestartBudgetExceededError):
    """The worker peer is unreachable (connection reset, EOF,
    injected partition, or timeouts past the retry budget).

    Subclasses :class:`RestartBudgetExceededError` deliberately: the
    fleet's existing failure handling — mark the replica down, reject
    its outstanding work typed, requeue the never-started part onto
    healthy siblings — is EXACTLY the right response to a partitioned
    host, and inheriting the type means every ``except
    RestartBudgetExceededError`` site in serve/fleet.py handles
    partitions with no dist-specific code."""


class PeerTimeoutError(PeerGoneError):
    """A call exceeded its deadline (after any retries).  Still peer
    loss — a peer that cannot answer within the budget is
    indistinguishable from a dead one, and waiting longer would stall
    the whole fleet's step loop."""


def _recv_exact(sock, n):
    """Read exactly ``n`` bytes or raise on EOF mid-read (the
    mid-stream-EOF case: a peer that died between frames raises
    PeerGone at the next read; one that died MID-frame raises here)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise PeerGoneError(
                f"peer closed the stream mid-frame ({len(buf)} of {n} "
                f"bytes read)", started=None)
        buf.extend(chunk)
    return bytes(buf)


class Conn:
    """One framed connection to a peer.  Single-threaded by design —
    the fleet drives every replica from its own loop, and the worker
    loop is strictly serial — so there is no locking, only framing.

    ``label`` is used in error messages and logs ("r2", "listener").
    """

    def __init__(self, sock, label=""):
        self.sock = sock
        self.label = label
        self.last_rx = time.monotonic()
        self._seq = 0
        self._log = get_channel("serve")
        # transport self-observability (attach_metrics): None until a
        # registry attaches — the unobserved cost is one truthiness
        # check per frame
        self._m_frames = None
        self._m_bytes = None
        self._m_retries = None
        self._m_rtt = None
        # TCP_NODELAY: RPCs are small request/response frames; Nagle
        # would add 40ms floors to every fleet step
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def attach_metrics(self, reg, peer, **labels) -> list:
        """Register this connection's per-peer transport metrics:
        ``serve.dist.{frames,bytes,retries}{peer=}`` counters (frames/
        bytes cover BOTH directions — everything that crossed this
        socket) and a ``serve.dist.rtt_s{peer=}`` histogram observed
        per successful ``call`` round trip (the bucket ladder starts
        at 10µs — loopback RPCs live far below the default 1ms
        floor).  Returns the metric objects so the owner can
        ``registry.remove(*them)`` on retire — the PR 15
        retire-unregisters contract."""
        lbl = dict(labels, peer=str(peer))
        self._m_frames = reg.counter(
            "serve.dist.frames",
            help="framed messages crossing this peer connection "
                 "(both directions)", **lbl)
        self._m_bytes = reg.counter(
            "serve.dist.bytes",
            help="wire bytes crossing this peer connection (headers "
                 "included, both directions)", **lbl)
        self._m_retries = reg.counter(
            "serve.dist.retries",
            help="RPC timeout retries re-sent on this connection",
            **lbl)
        self._m_rtt = reg.histogram(
            "serve.dist.rtt_s",
            help="RPC round-trip seconds to this peer (send -> "
                 "matching reply)",
            buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                     2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0), **lbl)
        return [self._m_frames, self._m_bytes, self._m_retries,
                self._m_rtt]

    # -- framing ---------------------------------------------------------
    def send(self, kind, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        head = _HEAD.pack(_MAGIC, PROTO_VERSION, kind,
                          zlib.crc32(payload) & 0xFFFFFFFF,
                          len(payload))
        try:
            self.sock.sendall(head + payload)
        except (OSError, ValueError) as e:
            raise PeerGoneError(
                f"send to peer {self.label or '?'} failed: {e!r}",
                started=None) from e
        if self._m_frames is not None:
            self._m_frames.inc()
            self._m_bytes.inc(_HEAD.size + len(payload))

    def recv(self, timeout=None):
        """One ``(kind, obj)`` frame.  ``timeout`` None blocks
        forever (the worker loop's idle state); a number raises
        :class:`PeerTimeoutError` on expiry."""
        try:
            self.sock.settimeout(timeout)
            head = _recv_exact(self.sock, _HEAD.size)
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"no frame from peer {self.label or '?'} within "
                f"{timeout}s", started=None) from e
        except OSError as e:
            raise PeerGoneError(
                f"recv from peer {self.label or '?'} failed: {e!r}",
                started=None) from e
        magic, proto, kind, crc, length = _HEAD.unpack(head)
        if magic != _MAGIC:
            raise TransportError(
                f"bad frame magic {magic!r} from peer "
                f"{self.label or '?'}: stream framing lost")
        if proto != PROTO_VERSION:
            raise TransportError(
                f"peer {self.label or '?'} speaks proto {proto}, this "
                f"side {PROTO_VERSION}: refuse rather than misparse")
        if length > _MAX_FRAME:
            raise TransportError(
                f"frame length {length} exceeds the {_MAX_FRAME} "
                f"bound: corrupted length field")
        try:
            payload = _recv_exact(self.sock, length)
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"frame body from peer {self.label or '?'} stalled",
                started=None) from e
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise TransportError(
                f"frame crc mismatch from peer {self.label or '?'}: "
                f"payload corrupted in transit")
        self.last_rx = time.monotonic()
        if self._m_frames is not None:
            self._m_frames.inc()
            self._m_bytes.inc(_HEAD.size + length)
        return kind, pickle.loads(payload)

    def age(self) -> float:
        """Seconds since the last successfully received frame — the
        piggybacked heartbeat the fleet's watchdog reads."""
        return time.monotonic() - self.last_rx

    # -- RPC (caller side) -----------------------------------------------
    def call(self, op, payload=None, timeout=60.0, retries=0,
             backoff=0.05, fault_site="serve.dist.rpc"):
        """Synchronous RPC: send ``CALL {seq, op, ...}``, wait for the
        matching ``REPLY``.  ``retries`` re-sends on TIMEOUT only
        (with exponential backoff) and must only be used for
        idempotent ops — a retried ``submit`` could double-admit.
        Checks the ``fault_site`` (default ``serve.dist.rpc``) first:
        a fired fault is a modeled partition and surfaces as
        :class:`PeerGoneError`.  Telemetry pulls pass their OWN site
        (``serve.dist.telemetry``) so a chaos test partitioning the
        control plane never has its injected fault consumed by a
        background telemetry call instead.
        """
        if _faults._armed:
            try:
                _faults.check(fault_site)
            except Exception as e:
                raise PeerGoneError(
                    f"partition injected on RPC {op!r} to peer "
                    f"{self.label or '?'} ({e!r})", started=None) from e
        attempt = 0
        while True:
            self._seq += 1
            seq = self._seq
            t_send = time.monotonic()
            self.send(MSG_CALL, {"seq": seq, "op": op,
                                 "payload": payload})
            try:
                while True:
                    kind, msg = self.recv(timeout)
                    if kind != MSG_REPLY:
                        # a stray one-way (late ship abort ack etc.)
                        # is not an error; skip it
                        continue
                    if msg.get("seq") != seq:
                        raise TransportError(
                            f"out-of-sequence reply from peer "
                            f"{self.label or '?'}: got "
                            f"{msg.get('seq')}, want {seq}")
                    if self._m_rtt is not None:
                        self._m_rtt.observe(
                            time.monotonic() - t_send)
                    return msg
            except PeerTimeoutError:
                if attempt >= retries:
                    raise
                attempt += 1
                if self._m_retries is not None:
                    self._m_retries.inc()
                self._log.warning(
                    "RPC %s to peer %s timed out; retry %d/%d", op,
                    self.label or "?", attempt, retries)
                time.sleep(backoff * (2 ** (attempt - 1)))

    def send_oneway(self, op, payload=None):
        """Fire-and-forget (ship frames, aborts): no reply, no seq."""
        self.send(MSG_ONEWAY, {"op": op, "payload": payload})

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class Listener:
    """The fleet's accept side: workers dial back here and prove
    membership with the shared ``token`` in their HELLO frame."""

    def __init__(self, host="127.0.0.1", port=0, token=b""):
        self.token = token
        self._log = get_channel("serve")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(64)
        self.host, self.port = self.sock.getsockname()

    def accept_worker(self, timeout=120.0):
        """Accept one worker connection and run its HELLO handshake.
        Returns ``(replica_idx, Conn)``.  The generous default timeout
        covers a spawned process importing jax from cold."""
        self.sock.settimeout(timeout)
        try:
            sock, addr = self.sock.accept()
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"no worker connected within {timeout}s",
                started=None) from e
        conn = Conn(sock)
        kind, hello = conn.recv(timeout=timeout)
        if kind != MSG_HELLO:
            conn.close()
            raise TransportError(
                f"first frame from {addr} was kind {kind}, not HELLO")
        if hello.get("token") != self.token \
                or hello.get("proto") != PROTO_VERSION:
            conn.close()
            raise TransportError(
                f"worker handshake from {addr} refused (token or "
                f"proto mismatch: proto={hello.get('proto')})")
        idx = int(hello["idx"])
        conn.label = f"r{idx}"
        self._log.info("worker r%d connected from %s", idx, addr)
        return idx, conn

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def connect_worker(host, port, token, idx, timeout=60.0) -> Conn:
    """Worker side of the handshake: dial the fleet's listener and
    introduce this replica."""
    sock = socket.create_connection((host, port), timeout=timeout)
    conn = Conn(sock, label="fleet")
    conn.send(MSG_HELLO, {"token": token, "idx": int(idx),
                          "proto": PROTO_VERSION})
    return conn
