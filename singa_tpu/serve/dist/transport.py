"""Framed socket transport for the distributed serve fleet.

One wire format for everything the fleet says to a worker peer:
length-prefixed, versioned, crc-checked frames over a plain TCP
socket.  Three message kinds ride it —

* ``CALL``/``REPLY`` — the synchronous control RPC the fleet drives a
  replica with (submit/step/build/export/...).  Every call carries a
  sequence number, a typed timeout, and an optional bounded
  retry-with-backoff for idempotent operations;
* ``ONEWAY`` — fire-and-forget messages that must not stall the
  sender: streamed KV ship frames (dist/fleet.py relays them to the
  destination while the source is still prefilling) and best-effort
  aborts/shutdowns;
* ``HELLO`` — the connect-time handshake: a worker proves it belongs
  to THIS fleet (shared token, compared constant-time, plus a single-
  use session nonce) and says which replica index it is;
* ``RESUME`` — the reconnect handshake: a worker whose socket dropped
  redials and offers to CONTINUE its session (fencing epoch +
  last-executed seq, HMAC-authenticated) instead of being respawned —
  the controller replays the one unacked CALL and routing resumes
  with warm jit caches.

Frame layout (all integers network byte order)::

    | magic 'STPU' | u8 proto | u8 kind | u32 crc32(payload) |
    | u64 length   | payload (pickle)                        |

A frame that fails the magic, version, crc, or length checks raises
:class:`TransportError` — the stream is unusable after that (framing
lost), so callers escalate to peer loss.  Socket-level failures map to
the PEER-LOSS family: :class:`PeerGoneError` subclasses
``RestartBudgetExceededError`` ON PURPOSE — to the fleet, a worker
that dropped off the network and a supervisor that spent its restart
budget are the same event ("this replica cannot serve; fail over"),
so every existing fleet path (admission, step, ship driving) handles a
partition with zero new code.  :class:`PeerTimeoutError` narrows it
for calls that exceeded their deadline after retries.

Heartbeats are PIGGYBACKED: every received frame refreshes the
connection's ``last_rx`` clock, so a busy peer never pays a separate
ping, and ``Conn.age()`` tells the fleet's watchdog how stale a quiet
peer is (it pings only those — serve/dist/fleet.py
``_check_watchdog``).

The ``serve.dist.rpc`` fault site is checked on the CALLER side of
every RPC (when armed): a fired fault is a modeled network partition —
the peer process is still alive, but this side treats it as gone,
which is exactly what a partition looks like from one end.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import random
import socket
import struct
import time
import zlib

from ...resilience import faults as _faults
from ...utils.logging import get_channel
from ..request import RestartBudgetExceededError

__all__ = ["PROTO_VERSION", "TransportError", "PeerGoneError",
           "PeerTimeoutError", "StaleEpochError",
           "NonIdempotentReplayError", "IDEMPOTENT_OPS", "Conn",
           "Listener", "MSG_CALL", "MSG_REPLY", "MSG_ONEWAY",
           "MSG_HELLO", "MSG_RESUME", "resume_auth"]

#: bump when the frame layout or the RPC envelope changes; a peer on a
#: different proto version fails the handshake typed instead of
#: misparsing frames
PROTO_VERSION = 1

MSG_CALL = 1
MSG_REPLY = 2
MSG_ONEWAY = 3
MSG_HELLO = 4
MSG_RESUME = 5

#: ops a reconnecting controller may safely RE-ISSUE under a fresh seq
#: when replay state has diverged (the worker may have executed the
#: lost call once already).  Everything else — submit, step, the ship/
#: build protocol — mutates worker state in a way a blind second
#: delivery would corrupt (double-admit, double-step), so divergence
#: on those aborts typed via :class:`NonIdempotentReplayError` and the
#: fleet's normal failover reconciles instead.
IDEMPOTENT_OPS = frozenset({
    "ping", "clock", "snapshot", "telemetry", "prefix_lookup",
    "validate", "cache_release", "session_release", "build_abandon",
    "abandon", "reconcile", "describe", "shutdown", "die",
})

_MAGIC = b"STPU"
_HEAD = struct.Struct("!4sBBIQ")
#: refuse absurd frame lengths before allocating: the largest honest
#: payload is a KV image of a test/bench pool (MBs); 1 GiB means a
#: corrupted length field, not a message
_MAX_FRAME = 1 << 30


class TransportError(RuntimeError):
    """The byte stream itself is broken: bad magic, proto-version
    skew, a crc mismatch, or a length-lying frame.  Framing is lost
    after this — the connection cannot be trusted for another
    message, so callers escalate to peer loss."""


class PeerGoneError(RestartBudgetExceededError):
    """The worker peer is unreachable (connection reset, EOF,
    injected partition, or timeouts past the retry budget).

    Subclasses :class:`RestartBudgetExceededError` deliberately: the
    fleet's existing failure handling — mark the replica down, reject
    its outstanding work typed, requeue the never-started part onto
    healthy siblings — is EXACTLY the right response to a partitioned
    host, and inheriting the type means every ``except
    RestartBudgetExceededError`` site in serve/fleet.py handles
    partitions with no dist-specific code."""


class PeerTimeoutError(PeerGoneError):
    """A call exceeded its deadline (after any retries).  Still peer
    loss — a peer that cannot answer within the budget is
    indistinguishable from a dead one, and waiting longer would stall
    the whole fleet's step loop."""


class StaleEpochError(RuntimeError):
    """The frame carried a fencing epoch older than the receiver's:
    the sender is a DEPOSED controller (someone adopted this worker
    under a higher epoch).  Refused typed on every op so split-brain
    dual routing is impossible by construction — a stale controller
    cannot step, submit to, or ship through a fenced worker.  Crosses
    the wire (registered in the worker's error table)."""


class NonIdempotentReplayError(PeerGoneError):
    """A reconnect found an unacked in-flight CALL whose replay state
    diverged AND whose op is not in :data:`IDEMPOTENT_OPS`: the worker
    may have executed it exactly once already, and re-issuing could
    double-admit or double-step.  Controller-side only — subclasses
    :class:`PeerGoneError` so the fleet's existing failover path
    (reject started work typed, requeue never-started) reconciles."""


def _full_jitter(rng, base, attempt, cap):
    """Full-jitter backoff: uniform in ``[0, min(base·2^attempt, cap))``
    — N workers redialing a restarted controller decorrelate instead
    of thundering in lockstep (the ``RetryPolicy.seed=None`` idiom)."""
    return rng.random() * min(base * (2.0 ** attempt), cap)


def resume_auth(token, nonce, idx, epoch, last_seq) -> str:
    """HMAC proving a RESUME frame was minted by a holder of the fleet
    token for THIS (nonce, replica, epoch, seq) tuple — a captured
    frame replays as garbage under any other session nonce."""
    key = token if isinstance(token, (bytes, bytearray)) else \
        str(token).encode()
    msg = f"{nonce}:{int(idx)}:{int(epoch)}:{int(last_seq)}".encode()
    return hmac.new(bytes(key), msg, hashlib.sha256).hexdigest()


def _recv_exact(sock, n):
    """Read exactly ``n`` bytes or raise on EOF mid-read (the
    mid-stream-EOF case: a peer that died between frames raises
    PeerGone at the next read; one that died MID-frame raises here)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise PeerGoneError(
                f"peer closed the stream mid-frame ({len(buf)} of {n} "
                f"bytes read)", started=None)
        buf.extend(chunk)
    return bytes(buf)


class Conn:
    """One framed connection to a peer.  Single-threaded by design —
    the fleet drives every replica from its own loop, and the worker
    loop is strictly serial — so there is no locking, only framing.

    ``label`` is used in error messages and logs ("r2", "listener").
    """

    def __init__(self, sock, label=""):
        self.sock = sock
        self.label = label
        self.last_rx = time.monotonic()
        self._seq = 0
        #: fencing epoch stamped into every CALL/ONEWAY envelope when
        #: set — workers refuse stale epochs typed (StaleEpochError)
        self.epoch = None
        #: the one unacked in-flight CALL ``(seq, op, payload)`` —
        #: what a reconnect must replay (the protocol is strictly
        #: serial, so there is never more than one)
        self._pending = None
        #: OS-entropy rng for full-jitter backoff — deliberately NOT
        #: seeded so concurrent redialers decorrelate
        self._rng = random.Random()
        self._log = get_channel("serve")
        # transport self-observability (attach_metrics): None until a
        # registry attaches — the unobserved cost is one truthiness
        # check per frame
        self._m_frames = None
        self._m_bytes = None
        self._m_retries = None
        self._m_rtt = None
        # TCP_NODELAY: RPCs are small request/response frames; Nagle
        # would add 40ms floors to every fleet step
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass

    def attach_metrics(self, reg, peer, **labels) -> list:
        """Register this connection's per-peer transport metrics:
        ``serve.dist.{frames,bytes,retries}{peer=}`` counters (frames/
        bytes cover BOTH directions — everything that crossed this
        socket) and a ``serve.dist.rtt_s{peer=}`` histogram observed
        per successful ``call`` round trip (the bucket ladder starts
        at 10µs — loopback RPCs live far below the default 1ms
        floor).  Returns the metric objects so the owner can
        ``registry.remove(*them)`` on retire — the PR 15
        retire-unregisters contract."""
        lbl = dict(labels, peer=str(peer))
        self._m_frames = reg.counter(
            "serve.dist.frames",
            help="framed messages crossing this peer connection "
                 "(both directions)", **lbl)
        self._m_bytes = reg.counter(
            "serve.dist.bytes",
            help="wire bytes crossing this peer connection (headers "
                 "included, both directions)", **lbl)
        self._m_retries = reg.counter(
            "serve.dist.retries",
            help="RPC timeout retries re-sent on this connection",
            **lbl)
        self._m_rtt = reg.histogram(
            "serve.dist.rtt_s",
            help="RPC round-trip seconds to this peer (send -> "
                 "matching reply)",
            buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3,
                     2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0), **lbl)
        return [self._m_frames, self._m_bytes, self._m_retries,
                self._m_rtt]

    # -- framing ---------------------------------------------------------
    def send(self, kind, obj):
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        head = _HEAD.pack(_MAGIC, PROTO_VERSION, kind,
                          zlib.crc32(payload) & 0xFFFFFFFF,
                          len(payload))
        try:
            self.sock.sendall(head + payload)
        except (OSError, ValueError) as e:
            raise PeerGoneError(
                f"send to peer {self.label or '?'} failed: {e!r}",
                started=None) from e
        if self._m_frames is not None:
            self._m_frames.inc()
            self._m_bytes.inc(_HEAD.size + len(payload))

    def recv(self, timeout=None):
        """One ``(kind, obj)`` frame.  ``timeout`` None blocks
        forever (the worker loop's idle state); a number raises
        :class:`PeerTimeoutError` on expiry."""
        try:
            self.sock.settimeout(timeout)
            head = _recv_exact(self.sock, _HEAD.size)
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"no frame from peer {self.label or '?'} within "
                f"{timeout}s", started=None) from e
        except OSError as e:
            raise PeerGoneError(
                f"recv from peer {self.label or '?'} failed: {e!r}",
                started=None) from e
        magic, proto, kind, crc, length = _HEAD.unpack(head)
        if magic != _MAGIC:
            raise TransportError(
                f"bad frame magic {magic!r} from peer "
                f"{self.label or '?'}: stream framing lost")
        if proto != PROTO_VERSION:
            raise TransportError(
                f"peer {self.label or '?'} speaks proto {proto}, this "
                f"side {PROTO_VERSION}: refuse rather than misparse")
        if length > _MAX_FRAME:
            raise TransportError(
                f"frame length {length} exceeds the {_MAX_FRAME} "
                f"bound: corrupted length field")
        try:
            payload = _recv_exact(self.sock, length)
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"frame body from peer {self.label or '?'} stalled",
                started=None) from e
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise TransportError(
                f"frame crc mismatch from peer {self.label or '?'}: "
                f"payload corrupted in transit")
        self.last_rx = time.monotonic()
        if self._m_frames is not None:
            self._m_frames.inc()
            self._m_bytes.inc(_HEAD.size + length)
        return kind, pickle.loads(payload)

    def age(self) -> float:
        """Seconds since the last successfully received frame — the
        piggybacked heartbeat the fleet's watchdog reads."""
        return time.monotonic() - self.last_rx

    # -- RPC (caller side) -----------------------------------------------
    def send_call(self, op, payload=None) -> int:
        """Low-level CALL send: allocate the next seq, stamp the
        fencing epoch (when set), RECORD the call as pending (so a
        reconnect knows exactly what to replay), and put the frame on
        the wire.  Returns the seq the caller must await."""
        self._seq += 1
        seq = self._seq
        env = {"seq": seq, "op": op, "payload": payload}
        if self.epoch is not None:
            env["epoch"] = self.epoch
        self._pending = (seq, op, payload)
        self.send(MSG_CALL, env)
        return seq

    def resend_pending(self) -> int:
        """Re-put the pending CALL on the (new, post-resume) wire
        under its ORIGINAL seq — first delivery if it never arrived,
        a reply-cache hit on the worker if it did."""
        seq, op, payload = self._pending
        env = {"seq": seq, "op": op, "payload": payload}
        if self.epoch is not None:
            env["epoch"] = self.epoch
        self.send(MSG_CALL, env)
        return seq

    def wait_reply(self, seq, timeout=60.0):
        """Wait for the REPLY matching ``seq``; clears the pending
        record on success.  Stray one-ways are skipped, a wrong-seq
        reply is a framing loss (TransportError)."""
        while True:
            kind, msg = self.recv(timeout)
            if kind != MSG_REPLY:
                # a stray one-way (late ship abort ack etc.) is not
                # an error; skip it
                continue
            if msg.get("seq") != seq:
                raise TransportError(
                    f"out-of-sequence reply from peer "
                    f"{self.label or '?'}: got {msg.get('seq')}, "
                    f"want {seq}")
            self._pending = None
            return msg

    def call(self, op, payload=None, timeout=60.0, retries=0,
             backoff=0.05, backoff_cap=2.0,
             fault_site="serve.dist.rpc"):
        """Synchronous RPC: send ``CALL {seq, op, ...}``, wait for the
        matching ``REPLY``.  ``retries`` re-sends on TIMEOUT only
        (full-jitter backoff capped at ``backoff_cap`` — lockstep
        retry storms decorrelate) and must only be used for idempotent
        ops — a retried ``submit`` could double-admit.  Checks the
        ``fault_site`` (default ``serve.dist.rpc``) first: a fired
        fault is a modeled partition and surfaces as
        :class:`PeerGoneError` with ``no_resume`` set — injected
        partitions must hit the failover path directly, never the
        reconnect window (the peer's socket never actually broke, so
        no redial is coming).  Telemetry pulls pass their OWN site
        (``serve.dist.telemetry``) so a chaos test partitioning the
        control plane never has its injected fault consumed by a
        background telemetry call instead.
        """
        if _faults._armed:
            try:
                _faults.check(fault_site)
            except Exception as e:
                err = PeerGoneError(
                    f"partition injected on RPC {op!r} to peer "
                    f"{self.label or '?'} ({e!r})", started=None)
                err.no_resume = True
                raise err from e
        attempt = 0
        while True:
            t_send = time.monotonic()
            seq = self.send_call(op, payload)
            try:
                msg = self.wait_reply(seq, timeout)
                if self._m_rtt is not None:
                    self._m_rtt.observe(time.monotonic() - t_send)
                return msg
            except PeerTimeoutError:
                if attempt >= retries:
                    raise
                attempt += 1
                if self._m_retries is not None:
                    self._m_retries.inc()
                self._log.warning(
                    "RPC %s to peer %s timed out; retry %d/%d", op,
                    self.label or "?", attempt, retries)
                time.sleep(_full_jitter(self._rng, backoff,
                                        attempt - 1, backoff_cap))

    def finish_pending(self, peer_last_seq, timeout=60.0):
        """Replay the one unacked in-flight CALL after a resume.

        The worker told us (in its RESUME frame) the last seq it
        EXECUTED.  Three cases against our pending ``(seq, op, ...)``:

        * ``seq <= peer_last_seq`` — the call arrived and ran; only
          the reply was lost.  Resend the SAME seq: the worker's
          reply cache answers from memory without re-executing
          (exactly-once by seq dedupe).
        * ``seq == peer_last_seq + 1`` — the call never arrived.
          Resend the same seq: this is first delivery, not a replay.
        * anything else — the seq spaces diverged (should not happen
          on a serial protocol; defensive).  Idempotent ops re-issue
          under a fresh seq; non-idempotent ops abort typed with
          :class:`NonIdempotentReplayError` so failover reconciles.

        Returns the reply message, or None when nothing was pending.
        """
        if self._pending is None:
            return None
        seq, op, payload = self._pending
        if seq <= peer_last_seq + 1:
            self.resend_pending()
            return self.wait_reply(seq, timeout)
        if op in IDEMPOTENT_OPS:
            self._pending = None
            self._seq = max(self._seq, peer_last_seq)
            return self.call(op, payload, timeout=timeout)
        self._pending = None
        raise NonIdempotentReplayError(
            f"cannot replay non-idempotent RPC {op!r} (seq {seq}) to "
            f"peer {self.label or '?'}: peer last executed seq "
            f"{peer_last_seq}; aborting typed rather than risking a "
            f"double execution", started=None)

    def send_oneway(self, op, payload=None):
        """Fire-and-forget (ship frames, aborts): no reply, no seq.
        Carries the fencing epoch when set — a fenced worker silently
        drops stale one-ways (there is no reply channel to refuse on)."""
        env = {"op": op, "payload": payload}
        if self.epoch is not None:
            env["epoch"] = self.epoch
        self.send(MSG_ONEWAY, env)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class Listener:
    """The fleet's accept side: workers dial back here and prove
    membership — HELLO with the shared ``token`` (compared constant-
    time), RESUME with an HMAC over a per-session nonce.  Nonces are
    single-use per listener: a captured handshake frame replayed
    against the same listener is refused."""

    def __init__(self, host="127.0.0.1", port=0, token=b""):
        self.token = token
        self._log = get_channel("serve")
        #: nonces already accepted — replaying a captured HELLO/RESUME
        #: frame (same nonce) is refused even with a valid token/HMAC
        self._seen_nonces = set()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(64)
        self.host, self.port = self.sock.getsockname()

    def _check_nonce(self, frame, addr, conn):
        nonce = frame.get("nonce")
        if not isinstance(nonce, str) or not nonce \
                or nonce in self._seen_nonces:
            conn.close()
            raise TransportError(
                f"handshake from {addr} refused: missing or replayed "
                f"session nonce")
        self._seen_nonces.add(nonce)
        # bound the set — a long-lived listener must not grow without
        # limit; dropping ancient nonces only re-opens replay of
        # frames older than 4096 handshakes, far past any socket's TTL
        if len(self._seen_nonces) > 4096:
            self._seen_nonces.pop()

    def accept_any(self, timeout=120.0):
        """Accept one inbound connection and classify its first frame.
        Returns ``(kind, frame, Conn)`` where kind is MSG_HELLO (fresh
        worker) or MSG_RESUME (a worker redialing after a drop) — the
        caller routes them to registration vs session resume.  Both
        handshakes are verified here: token via ``hmac.compare_digest``
        for HELLO, the nonce-keyed HMAC for RESUME."""
        self.sock.settimeout(timeout)
        try:
            sock, addr = self.sock.accept()
        except socket.timeout as e:
            raise PeerTimeoutError(
                f"no worker connected within {timeout}s",
                started=None) from e
        conn = Conn(sock)
        kind, frame = conn.recv(timeout=timeout)
        if kind == MSG_HELLO:
            tok = frame.get("token")
            ours = self.token if isinstance(self.token, bytes) \
                else str(self.token).encode()
            theirs = tok if isinstance(tok, bytes) else \
                str(tok).encode() if tok is not None else b""
            if not hmac.compare_digest(theirs, ours) \
                    or frame.get("proto") != PROTO_VERSION:
                conn.close()
                raise TransportError(
                    f"worker handshake from {addr} refused (token or "
                    f"proto mismatch: proto={frame.get('proto')})")
            self._check_nonce(frame, addr, conn)
        elif kind == MSG_RESUME:
            if frame.get("proto") != PROTO_VERSION:
                conn.close()
                raise TransportError(
                    f"resume from {addr} refused (proto "
                    f"{frame.get('proto')})")
            want = resume_auth(self.token, frame.get("nonce", ""),
                               frame.get("idx", -1),
                               frame.get("epoch", -1),
                               frame.get("last_seq", -1))
            got = frame.get("auth", "")
            if not isinstance(got, str) \
                    or not hmac.compare_digest(got, want):
                conn.close()
                raise TransportError(
                    f"resume from {addr} refused (bad auth)")
            self._check_nonce(frame, addr, conn)
        else:
            conn.close()
            raise TransportError(
                f"first frame from {addr} was kind {kind}, not "
                f"HELLO/RESUME")
        idx = int(frame["idx"])
        conn.label = f"r{idx}"
        self._log.info(
            "worker r%d %s from %s", idx,
            "connected" if kind == MSG_HELLO else "resuming", addr)
        return kind, frame, conn

    def accept_worker(self, timeout=120.0):
        """Accept one FRESH worker connection (HELLO handshake).
        Returns ``(replica_idx, Conn)``.  The generous default timeout
        covers a spawned process importing jax from cold.  A RESUME
        arriving here (a redialing worker racing a fresh spawn) is
        refused — the caller's accept loop owns resume routing."""
        kind, frame, conn = self.accept_any(timeout)
        if kind != MSG_HELLO:
            conn.close()
            raise TransportError(
                f"expected a fresh worker HELLO, got a RESUME from "
                f"r{frame.get('idx')}")
        return int(frame["idx"]), conn

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


def connect_worker(host, port, token, idx, timeout=60.0) -> Conn:
    """Worker side of the handshake: dial the fleet's listener and
    introduce this replica.  The fresh nonce makes the frame single-
    use — captured HELLOs cannot open a second session."""
    sock = socket.create_connection((host, port), timeout=timeout)
    conn = Conn(sock, label="fleet")
    conn.send(MSG_HELLO, {"token": token, "idx": int(idx),
                          "proto": PROTO_VERSION,
                          "nonce": os.urandom(16).hex()})
    return conn


def resume_worker(host, port, token, idx, epoch, last_seq,
                  timeout=5.0):
    """Worker side of session resume: redial the listener and offer to
    continue the existing session — ``epoch`` is the fencing epoch the
    worker last obeyed, ``last_seq`` the last CALL seq it EXECUTED
    (the controller replays anything after it).  Authenticated by an
    HMAC over (nonce, idx, epoch, last_seq) so membership is proven
    without the token itself crossing the wire again.  Returns
    ``(conn, ack)`` where ack is the controller's MSG_RESUME verdict
    — ``{"ok": bool, "epoch": int}``."""
    sock = socket.create_connection((host, port), timeout=timeout)
    conn = Conn(sock, label="fleet")
    nonce = os.urandom(16).hex()
    conn.send(MSG_RESUME, {
        "idx": int(idx), "proto": PROTO_VERSION, "nonce": nonce,
        "epoch": int(epoch), "last_seq": int(last_seq),
        "auth": resume_auth(token, nonce, idx, epoch, last_seq)})
    kind, ack = conn.recv(timeout=timeout)
    if kind != MSG_RESUME:
        conn.close()
        raise TransportError(
            f"resume ack was kind {kind}, not RESUME")
    return conn, ack
