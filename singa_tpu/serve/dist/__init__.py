"""Multi-host serving: the fleet across the process boundary.

* :mod:`~singa_tpu.serve.dist.transport` — framed socket transport
  (length-prefixed, versioned, crc-checked; typed timeout/retry;
  piggybacked heartbeats);
* :mod:`~singa_tpu.serve.dist.worker` — the replica worker loop: one
  supervised engine behind the RPC dispatch, built from a picklable
  :class:`ModelSpec`;
* :mod:`~singa_tpu.serve.dist.fleet` — :class:`DistFleet`, a
  :class:`~singa_tpu.serve.fleet.ServeFleet` whose replicas are worker
  processes (or threads), with wire KV shipping — bulk images and
  layer-wise streamed frames — and the cross-host residency directory.

Controller survivability: a dropped socket enters a bounded
reconnect-with-resume window instead of condemning the peer (the
worker redials, the session resumes, the one unacked CALL replays
exactly-once); workers journal per-request progress and PARK finished
results when the controller vanishes; ``DistFleet.adopt`` attaches a
successor controller to the live workers under a bumped fencing epoch
— the dead controller's frames are refused typed
(:class:`StaleEpochError`), parked results re-deliver exactly once,
and routing resumes with warm jit caches.

See docs/SERVING.md "Multi-host serving" and "Controller recovery".
"""

from .fleet import DistFleet, DistSession, RemoteSupervisor
from .transport import (IDEMPOTENT_OPS, PROTO_VERSION, Conn, Listener,
                        NonIdempotentReplayError, PeerGoneError,
                        PeerTimeoutError, StaleEpochError,
                        TransportError, resume_worker)
from .worker import ModelSpec, gpt2_spec, worker_main

__all__ = [
    "DistFleet", "DistSession", "RemoteSupervisor",
    "ModelSpec", "gpt2_spec", "worker_main",
    "PROTO_VERSION", "IDEMPOTENT_OPS", "Conn", "Listener",
    "PeerGoneError", "PeerTimeoutError", "TransportError",
    "StaleEpochError", "NonIdempotentReplayError", "resume_worker",
]
