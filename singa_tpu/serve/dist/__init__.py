"""Multi-host serving: the fleet across the process boundary.

* :mod:`~singa_tpu.serve.dist.transport` — framed socket transport
  (length-prefixed, versioned, crc-checked; typed timeout/retry;
  piggybacked heartbeats);
* :mod:`~singa_tpu.serve.dist.worker` — the replica worker loop: one
  supervised engine behind the RPC dispatch, built from a picklable
  :class:`ModelSpec`;
* :mod:`~singa_tpu.serve.dist.fleet` — :class:`DistFleet`, a
  :class:`~singa_tpu.serve.fleet.ServeFleet` whose replicas are worker
  processes (or threads), with wire KV shipping — bulk images and
  layer-wise streamed frames — and the cross-host residency directory.

See docs/SERVING.md "Multi-host serving".
"""

from .fleet import DistFleet, DistSession, RemoteSupervisor
from .transport import (PROTO_VERSION, Conn, Listener, PeerGoneError,
                        PeerTimeoutError, TransportError)
from .worker import ModelSpec, gpt2_spec, worker_main

__all__ = [
    "DistFleet", "DistSession", "RemoteSupervisor",
    "ModelSpec", "gpt2_spec", "worker_main",
    "PROTO_VERSION", "Conn", "Listener", "PeerGoneError",
    "PeerTimeoutError", "TransportError",
]
