"""Multi-host serve fleet: replicas across the process boundary.

:class:`DistFleet` IS a :class:`~singa_tpu.serve.fleet.ServeFleet` —
it subclasses it and overrides exactly the seams where "replica" stops
meaning "an object in this process": construction
(``_new_supervisor`` spawns a worker process and returns an RPC
proxy), the step loop (``_step_replicas`` issues every replica's step
RPC before collecting any reply, so remote engines decode
concurrently), the watchdog (idle peers get pinged instead of
heartbeat-latched), and the KV ship path (images cross the wire;
streamed ships relay per-layer frames while the source is still
prefilling).  Everything else — the Router, failover/requeue, hedging,
sessions, disaggregated roles, the Autoscaler, the soak harness — runs
UNMODIFIED on top, which is the point: the fleet surface is the same,
only the replicas moved out.

The proxy layer:

* :class:`RemoteSupervisor` duck-types
  :class:`~singa_tpu.serve.supervisor.EngineSupervisor`: ``submit``
  returns a real parent-side :class:`RequestHandle` that resolves from
  step-reply deltas; the ship API (start/advance/export/admit/abandon)
  maps 1:1 onto worker RPCs.  Typed errors cross the wire and
  reconstruct to their own classes, ``started`` included — the fleet's
  requeue-safety decision depends on it;
* :class:`_RemoteEngineView` shims the handful of ``sup.engine.*``
  attributes the base fleet reads (scheduler depth, occupancy, stats,
  arena pressure, prefix-cache lookup) from cached step-reply views,
  so routing costs no extra round trips.  ``prefix_cache.lookup`` IS
  the residency directory's verify hook: it asks the remote tree over
  RPC, and a dead or partitioned host answers "no blocks" — the fleet
  prunes the stale hint and serves cold-but-correct, never a wrong
  token;
* a partitioned peer surfaces as
  :class:`~singa_tpu.serve.dist.transport.PeerGoneError`, which
  subclasses ``RestartBudgetExceededError`` — every existing fleet
  failover path handles it with zero dist-specific code.  Requests
  lost to a partition are requeued iff no token was DELIVERED to the
  caller (``started=False``): same seed → same chain → the replay is
  byte-identical.

Streamed shipping (vLLM-style layer-wise KV streaming, fleet-level):
each ``build_advance`` reply carries the newly prefilled lanes sliced
per (leaf, layer); the fleet relays them to the chosen destination as
fire-and-forget ``ship_frame`` messages while the source prefills the
NEXT chunk — ship latency hides behind prefill compute, which is what
cuts the warm-TTFT floor for long documents.  The destination stages
frames in host buffers and only at ``ship_commit`` seals them into a
:class:`~singa_tpu.serve.kvimage.KVImage` carrying the source's
pack-time crc32: a half-shipped or bit-flipped stream fails typed at
admit and the request replays cold.  The ``serve.dist.frame`` fault
site fires mid-relay to model exactly that.

``spawn="process"`` runs each worker under multiprocessing spawn (real
isolation — the CI smoke and deployment shape); ``spawn="thread"``
runs the same worker loop, same sockets, same wire format in threads
of this process (fast enough for tier-1 tests, and in-process fault
sites reach the worker engines).
"""

from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from ...observe import federate as _federate
from ...observe import requests as _reqs
from ...observe import trace as _trace
from ...observe.federate import ClockSync, FleetTelemetry
from ...observe.timeseries import WindowRing
from ...resilience import faults as _faults
from ..fleet import ServeFleet, _Route
from ..kvimage import KVImage, KVImageError
from ..prefix import SessionHandle
from ..request import (EngineFailedError, RequestHandle,
                       RestartBudgetExceededError)
from .transport import (MSG_RESUME, Listener, PeerGoneError,
                        PeerTimeoutError, StaleEpochError,
                        TransportError)
from .worker import (ModelSpec, dump_request, load_exc, load_request,
                     worker_main)
from ..request import GenerationResult

__all__ = ["DistFleet", "RemoteSupervisor"]

_ship_ids = itertools.count(1)


class DistSession(SessionHandle):
    """Parent-side handle for a session pinned in a WORKER's radix
    tree.  Owns the host tokens (continuations build valid requests
    against any replica — cold elsewhere, warm on the sticky one);
    ``release`` unpins on the worker, best-effort (a dead worker's
    pins died with its tree)."""

    def __init__(self, tokens, sup, sid):
        super().__init__(tokens)
        self._sup = sup
        self._sid = sid

    def release(self):
        sid, self._sid = self._sid, None
        if sid is not None:
            self._sup.session_release(sid)


class _ViewSched:
    __slots__ = ("queue_depth", "max_queue_depth")

    def __init__(self, max_queue_depth):
        self.queue_depth = 0
        self.max_queue_depth = max_queue_depth


class _ViewStats:
    __slots__ = ("engine_label", "tpot_ewma", "_sup")

    def __init__(self, sup, engine_label):
        self._sup = sup
        self.engine_label = engine_label
        self.tpot_ewma = None

    def snapshot(self) -> dict:
        return self._sup._snapshot()


class _ViewArena:
    __slots__ = ("block_size", "num_blocks", "quant", "blocks_used")

    def __init__(self, block_size, num_blocks, quant):
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.quant = quant
        self.blocks_used = 0


class _ViewCache:
    """The remote radix tree, seen through its two fleet-facing verbs.
    ``lookup`` is the residency directory's verify hook: a partitioned
    or dead peer answers as if it held NOTHING, so the fleet prunes
    the stale hint and degrades cold-but-correct."""

    __slots__ = ("_sup", "cached_blocks")

    def __init__(self, sup):
        self._sup = sup
        self.cached_blocks = 0

    def lookup(self, tokens):
        return [True] * self._sup._prefix_lookup(tokens)

    def release(self, path_id):
        self._sup._cache_release(path_id)


class _RemoteEngineView:
    """The ``sup.engine`` surface the base fleet reads, backed by
    init-ack statics and cached step-reply load samples — routing
    never pays a round trip."""

    def __init__(self, sup, ack):
        self._sup = sup
        self.max_slots = ack["max_slots"]
        self.max_len = ack["max_len"]
        self._budget = ack["budget"]
        self.scheduler = _ViewSched(ack["max_queue_depth"])
        self.stats = _ViewStats(sup, f"r{sup._idx}:"
                                     f"{ack['engine_label']}")
        self.paged_arena = (_ViewArena(ack["block_size"],
                                       ack["num_blocks"],
                                       ack["quant"])
                            if ack["has_arena"] else None)
        self.prefix_cache = _ViewCache(sup) if ack["has_cache"] \
            else None
        self.live_request_ids = set()
        self.live_slots = 0
        self._closed = False
        self._failed = False

    def validate_request(self, request):
        self._sup._validate(request)

    def __exit__(self, exc_type, *a):
        self._sup.close(force=True)
        return False


class _RemoteJob:
    """Parent-side proxy of a worker's prefix-build job.  ``engine``
    is the owning supervisor's engine VIEW — the base fleet's
    ``job.engine is not rep.sup.engine`` staleness check works
    verbatim (a revived replica's new view never matches an old
    job's)."""

    __slots__ = ("job_id", "hit", "n_goal", "stream_meta", "engine")

    def __init__(self, job_id, hit, n_goal, stream_meta, engine):
        self.job_id = job_id
        self.hit = hit
        self.n_goal = n_goal
        self.stream_meta = stream_meta
        self.engine = engine


class RemoteSupervisor:
    """RPC proxy presenting the :class:`EngineSupervisor` surface for
    one worker replica.  Single-threaded like everything fleet-side;
    all state deltas arrive on RPC replies."""

    def __init__(self, fleet, idx, conn, proc, ack):
        self._fleet = fleet
        self._idx = idx
        self._conn = conn
        self._proc = proc
        self._clock = fleet._clock
        self.engine = _RemoteEngineView(self, ack)
        self.restarts = 0
        self._inner = {}     # rid -> parent-side RequestHandle
        self._order = []
        self._streamed = set()  # rids with tokens DELIVERED here
        self.pid = ack.get("pid")
        lbl = dict(fleet=fleet.fleet_label, replica=str(idx))
        reg = fleet._reg
        self._c_rpcs = reg.counter(
            "serve.dist.rpcs",
            help="control RPCs issued to this worker peer", **lbl)
        self._c_rpc_errors = reg.counter(
            "serve.dist.rpc_errors",
            help="RPCs lost to peer failure (partition, timeout, "
                 "broken framing)", **lbl)
        self._c_frames = reg.counter(
            "serve.dist.frames",
            help="streamed KV ship frames relayed TO this peer", **lbl)
        self._c_frame_bytes = reg.counter(
            "serve.dist.frame_bytes",
            help="host bytes of streamed KV frames relayed TO this "
                 "peer", **lbl)
        fleet._dist_registered += [self._c_rpcs, self._c_rpc_errors,
                                   self._c_frames, self._c_frame_bytes]

    # -- plumbing --------------------------------------------------------
    def _rpc(self, op, payload=None, timeout=None, retries=0):
        if self.engine._closed:
            raise PeerGoneError(
                f"worker r{self._idx} is closed", started=None)
        self._c_rpcs.inc()
        try:
            msg = self._conn.call(
                op, payload,
                timeout=(timeout if timeout is not None
                         else self._fleet._rpc_timeout),
                retries=retries)
        except TransportError as e:
            # framing lost: the stream cannot be trusted — peer loss
            # unless the worker redials inside the reconnect window
            self._c_rpc_errors.inc()
            cause = PeerGoneError(
                f"worker r{self._idx} framing lost: {e}",
                started=None)
            cause.__cause__ = e
            msg = self._resume_and_replay(cause, timeout)
        except PeerGoneError as e:
            self._c_rpc_errors.inc()
            msg = self._resume_and_replay(e, timeout)
        if not msg["ok"]:
            raise load_exc(msg["err"])
        return msg["value"]

    def _resume_and_replay(self, cause, timeout=None):
        """A socket-level loss mid-RPC: hold the replica inside its
        reconnect window instead of condemning it.  If the worker
        redials in time, replay the unacked CALL (exactly-once — the
        worker's reply cache dedupes by seq) and return its reply;
        otherwise re-raise ``cause`` into the existing PeerGone
        failover path.  Injected partition faults carry ``no_resume``
        and always escalate — the peer's socket never actually broke,
        so no redial is coming."""
        if getattr(cause, "no_resume", False) or self.engine._closed:
            raise cause
        frame = self._fleet._resume_peer(self)
        if frame is None:
            raise cause
        try:
            msg = self._conn.finish_pending(
                int(frame["last_seq"]),
                timeout=(timeout if timeout is not None
                         else self._fleet._rpc_timeout))
        except TransportError as e:
            raise PeerGoneError(
                f"worker r{self._idx} framing lost during replay: "
                f"{e}", started=None) from e
        if msg is None:
            raise cause
        self._fleet._c_resumed.inc()
        return msg

    def _apply_view(self, v):
        eng = self.engine
        eng.scheduler.queue_depth = v["queue_depth"]
        eng.live_slots = v["live_slots"]
        eng.stats.tpot_ewma = v["tpot_ewma"]
        eng.live_request_ids = set(v["live_rids"])
        self.restarts = v.get("restarts", self.restarts)
        if eng.paged_arena is not None \
                and v["blocks_used"] is not None:
            eng.paged_arena.blocks_used = v["blocks_used"]
        if eng.prefix_cache is not None \
                and v.get("cached_blocks") is not None:
            eng.prefix_cache.cached_blocks = v["cached_blocks"]

    def _apply_tokens(self, tokens):
        for rid, tok in tokens:
            h = self._inner.get(rid)
            if h is None or h.request.on_token is None:
                continue
            self._streamed.add(rid)
            try:
                h.request.on_token(h.request, tok)
            except Exception:
                # a raising CLIENT callback: the worker engine cannot
                # see it (delivery happens here); drop the token
                # stream rather than wedge the whole fleet step
                pass

    def _apply_resolved(self, resolved):
        for rid, out in resolved.items():
            h = self._inner.pop(rid, None)
            if h is None or h.done():
                continue
            if "err" in out:
                h._reject(load_exc(out["err"]))
                if self._fleet._spawn_mode == "process":
                    # thread mode: the worker engine's own reject site
                    # already emitted the instant into the SHARED trace
                    _trace.event(
                        "serve/request_rejected", cat="serve",
                        request=rid, reason=type(h._error).__name__,
                        replica=self._idx)
                if _reqs._active \
                        and self._fleet._spawn_mode == "process":
                    _reqs._ledger.on_reject(
                        rid, t=self._clock(),
                        reason=type(h._error).__name__,
                        engine=self.engine.stats.engine_label,
                        started=getattr(h._error, "started", None))
            else:
                h._finish(self._load_result(out["result"]))
                if _reqs._active \
                        and self._fleet._spawn_mode == "process":
                    r = h._result
                    _reqs._ledger.on_retire(
                        rid, engine=self.engine.stats.engine_label,
                        t=self._clock(),
                        finish_reason=r.finish_reason,
                        tokens=len(r.tokens))
        live = set(self._inner)
        self._order = [r for r in self._order if r in live]

    def _load_result(self, d):
        sess = None
        if d["session"] is not None:
            sess = DistSession(d["session"]["tokens"], self,
                               d["session"]["sid"])
        return GenerationResult(
            request_id=d["request_id"],
            tokens=[int(t) for t in d["tokens"]],
            finish_reason=d["finish_reason"], ttft=d["ttft"],
            tpot=d["tpot"], queue_time=d["queue_time"],
            admitted_step=d["admitted_step"],
            finished_step=d["finished_step"], session=sess)

    # -- EngineSupervisor surface ---------------------------------------
    @property
    def pending(self) -> bool:
        return bool(self._inner)

    def submit(self, request) -> RequestHandle:
        d = dump_request(request, self._clock)
        reply = self._rpc("submit", {"request": d})
        handle = RequestHandle(request)
        rid = request.request_id
        self._inner[rid] = handle
        self._order.append(rid)
        self._apply_view(reply["view"])
        if _reqs._active and self._fleet._spawn_mode == "process":
            # the worker's engine opened the hop in ITS process;
            # mirror a minimal hop here so the parent ledger sees the
            # request at all (thread mode shares the ledger — the
            # worker's own hop is already visible, skip the mirror)
            _reqs._ledger.on_submit(
                rid, engine=self.engine.stats.engine_label,
                t=self._clock(),
                prompt_len=len(request.prompt_ids),
                max_new_tokens=request.max_new_tokens)
        return handle

    def step_begin(self) -> int:
        """Send this replica's step CALL without waiting for the
        reply — DistFleet._step_replicas overlaps every peer's step.
        Checks the ``serve.dist.rpc`` partition fault exactly like a
        synchronous call would.  A send-side socket loss tries the
        reconnect window and re-sends the SAME seq on the new
        socket."""
        if self.engine._closed:
            raise PeerGoneError(
                f"worker r{self._idx} is closed", started=None)
        if _faults._armed:
            try:
                _faults.check("serve.dist.rpc")
            except Exception as e:
                self._c_rpc_errors.inc()
                err = PeerGoneError(
                    f"partition injected on step RPC to worker "
                    f"r{self._idx} ({e!r})", started=None)
                err.no_resume = True
                raise err from e
        self._c_rpcs.inc()
        try:
            return self._conn.send_call("step")
        except PeerGoneError as e:
            self._c_rpc_errors.inc()
            if getattr(e, "no_resume", False):
                raise
            frame = self._fleet._resume_peer(self)
            if frame is None:
                raise
            self._fleet._c_resumed.inc()
            return self._conn.resend_pending()

    def step_finish(self, seq):
        """Collect the reply for :meth:`step_begin` and apply its
        deltas (streamed tokens, resolved handles, the load view).
        A recv-side socket loss tries the reconnect window and
        replays the step call (the worker's reply cache dedupes)."""
        try:
            msg = self._conn.wait_reply(seq, self._fleet._rpc_timeout)
        except TransportError as e:
            self._c_rpc_errors.inc()
            cause = PeerGoneError(
                f"worker r{self._idx} framing lost: {e}",
                started=None)
            cause.__cause__ = e
            msg = self._resume_and_replay(cause)
        except PeerGoneError as e:
            self._c_rpc_errors.inc()
            msg = self._resume_and_replay(e)
        if not msg["ok"]:
            raise load_exc(msg["err"])
        reply = msg["value"]
        self._apply_tokens(reply["tokens"])
        self._apply_resolved(reply["resolved"])
        self._apply_view(reply["view"])
        if reply["budget"] is not None:
            # the worker's supervisor spent its restart budget: its
            # outstanding handles were rejected typed in `resolved`;
            # surface the replica-level death to the fleet
            raise load_exc(reply["budget"])
        return self.pending

    def step(self) -> bool:
        return self.step_finish(self.step_begin())

    def abandon(self, reason="fleet failover"):
        """Failover entry point.  Worker reachable: the REAL
        supervisor abandons (engine-truth ``started`` semantics) and
        the typed rejections apply here.  Worker unreachable (the
        partition case): resolve locally — ``started`` is True iff a
        token was DELIVERED to the caller, because delivery is the
        only thing the caller can observe; an undelivered request
        replays byte-identically (same seed, same chain)."""
        try:
            reply = self._rpc("abandon", {"reason": str(reason)},
                              timeout=10.0)
            self._apply_tokens(reply["tokens"])
            self._apply_resolved(reply["resolved"])
        except (PeerGoneError, RestartBudgetExceededError):
            self._local_abandon(reason)

    def _local_abandon(self, reason):
        for rid in list(self._order):
            h = self._inner.pop(rid, None)
            if h is None or h.done():
                continue
            started = rid in self._streamed
            h._reject(EngineFailedError(
                f"{rid}: worker r{self._idx} lost ({reason})",
                request_id=rid, started=started))
            # the worker is UNREACHABLE: nothing on its side can
            # record this rejection — the controller is the authority
            # on the delivery-started verdict, so it lands here
            _trace.event("serve/request_rejected", cat="serve",
                         request=rid, reason="peer_lost",
                         replica=self._idx, started=started)
            if _reqs._active:
                _reqs._ledger.on_reject(
                    rid, t=self._clock(), reason="peer_lost",
                    engine=self.engine.stats.engine_label,
                    started=started)
        self._order = []

    # -- ship API (the fleet's _drive_ships speaks this) -----------------
    def start_prefix_build(self, prompt_ids):
        reply = self._rpc("build_start", {
            "prompt_ids": np.asarray(prompt_ids, np.int32),
            "stream": self._fleet.stream_ships})
        if reply["job_id"] is None:
            return None
        return _RemoteJob(reply["job_id"], reply["hit"],
                          reply["n_goal"], reply["stream_meta"],
                          self.engine)

    def advance_prefix_build(self, job, max_tokens=None, rid=None):
        stream = self._fleet._ship_streams.get(rid)
        reply = self._rpc("build_advance", {
            "job_id": job.job_id, "budget": max_tokens, "rid": rid,
            "stream": stream is not None})
        if reply["status"] == "rebuilt":
            return None
        if stream is not None and reply["frames"]:
            self._relay_frames(rid, stream, reply["frames"])
        return reply["status"] == "done"

    def _relay_frames(self, rid, stream, frames):
        """Forward the source's newly built lanes to the streamed
        ship's destination, fire-and-forget — overlapped with the
        source's NEXT prefill chunk.  The ``serve.dist.frame`` fault
        fires here: a half-shipped image.  A destination lost
        mid-relay is marked down and the failure surfaces as a plain
        RuntimeError so the drive loop requeues the request cold
        WITHOUT condemning the healthy source."""
        dst_sup, ship_id = stream
        t0 = self._clock()
        try:
            for (li, layer, lo, hi, data) in frames:
                if _faults._armed:
                    _faults.check("serve.dist.frame")
                dst_sup._conn.send_oneway("ship_frame", {
                    "ship_id": ship_id, "leaf": li, "layer": layer,
                    "lo": lo, "hi": hi, "bytes": data})
                dst_sup._c_frames.inc()
                dst_sup._c_frame_bytes.inc(len(data))
            # wire time spent HERE is overlapped with the source's
            # next prefill chunk — the hidden half of the ship
            fleet = self._fleet
            fleet._ship_hidden[rid] = (
                fleet._ship_hidden.get(rid, 0.0)
                + (self._clock() - t0))
        except PeerGoneError as e:
            dst_sup._c_rpc_errors.inc()
            fleet = self._fleet
            fleet._ship_streams.pop(rid, None)
            fleet._mark_down(fleet._replicas[dst_sup._idx], e)
            raise RuntimeError(
                f"streamed ship destination r{dst_sup._idx} lost "
                f"mid-relay: {e}") from e

    def export_prefix_image(self, job):
        reply = self._rpc("build_export", {"job_id": job.job_id})
        return KVImage.from_bytes(reply["image"]), reply["resident"]

    def export_ship_meta(self, job):
        """Streamed-path export: the lanes already crossed as frames;
        fetch only the image identity (header/crc/geometry) and the
        residency verdict."""
        reply = self._rpc("build_export_meta", {"job_id": job.job_id})
        return reply["meta"], reply["resident"]

    def admit_prefix_image(self, tokens, image):
        reply = self._rpc("admit_image", {
            "tokens": np.asarray(tokens, np.int32),
            "image": image.to_bytes()})
        return reply["path"]

    def abandon_prefix_build(self, job):
        try:
            self._rpc("build_abandon", {"job_id": job.job_id},
                      timeout=10.0)
        except (PeerGoneError, RestartBudgetExceededError):
            pass  # best-effort cleanup on a dying peer

    def ship_begin(self, ship_id, meta):
        self._conn.send_oneway("ship_begin", {"ship_id": ship_id,
                                              "meta": meta})

    def ship_abort(self, ship_id):
        try:
            self._conn.send_oneway("ship_abort",
                                   {"ship_id": ship_id})
        except PeerGoneError:
            pass  # its staging died with it

    def ship_commit(self, ship_id, tokens, meta):
        reply = self._rpc("ship_commit", {
            "ship_id": ship_id,
            "tokens": np.asarray(tokens, np.int32),
            "header": meta["header"], "checksum": meta["checksum"],
            "n_data": meta["n_data"],
            "block_size": meta["block_size"], "quant": meta["quant"],
            "k_leaves": meta["k_leaves"]})
        return reply["path"]

    # -- view-shim backends ----------------------------------------------
    def _prefix_lookup(self, tokens) -> int:
        try:
            return self._rpc("prefix_lookup", {
                "tokens": np.asarray(tokens, np.int32)})["n"]
        except (PeerGoneError, RestartBudgetExceededError):
            return 0  # unreachable == holds nothing: hint gets pruned

    def _cache_release(self, path_id):
        self._rpc("cache_release", {"path": path_id}, timeout=10.0)

    def _validate(self, request):
        self._rpc("validate",
                  {"request": dump_request(request, self._clock)})

    def session_release(self, sid):
        try:
            self._rpc("session_release", {"sid": sid}, timeout=10.0)
        except (PeerGoneError, RestartBudgetExceededError):
            pass  # a dead worker's pins died with its tree

    def _snapshot(self) -> dict:
        try:
            return self._rpc("snapshot", timeout=10.0)["stats"]
        except (PeerGoneError, RestartBudgetExceededError):
            return {"engine_label": self.engine.stats.engine_label,
                    "unreachable": True}

    def ping(self):
        self._rpc("ping", timeout=5.0)

    # -- lifecycle -------------------------------------------------------
    def close(self, force=False):
        if self.engine._closed:
            return
        self.engine._closed = True
        try:
            self._conn.call("shutdown", {"force": force},
                            timeout=10.0)
        except (PeerGoneError, TransportError):
            pass
        self._conn.close()
        if self._proc is not None:
            # adopted workers have no spawn handle to reap — they were
            # spawned by the controller this one replaced
            self._fleet._graveyard.append(self._proc)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        self.close(force=True)
        return False


class DistFleet(ServeFleet):
    """A :class:`ServeFleet` whose replicas are worker processes.

    >>> spec = gpt2_spec(model)          # serve/dist/worker.py
    >>> fleet = DistFleet(spec, replicas=2, spawn="process",
    ...                   max_slots=4)
    >>> h = fleet.submit(GenerationRequest(prompt, max_new_tokens=8))
    >>> fleet.run_until_complete()

    ``spec`` is a :class:`~singa_tpu.serve.dist.worker.ModelSpec`
    (factory + weight states): every worker builds the SAME model, so
    token streams are byte-identical to a single-process fleet over
    the same replica count.  ``spawn`` picks ``"process"``
    (multiprocessing spawn — real isolation) or ``"thread"`` (same
    wire protocol over loopback, worker loops in threads — the
    tier-1-test configuration).  ``stream_ships`` enables layer-wise
    streamed KV shipping (on by default); bulk single-image shipping
    is the fallback and the resident-hit path either way."""

    def __init__(self, spec, replicas=2, spawn="thread",
                 stream_ships=True, rpc_timeout=60.0,
                 heartbeat_timeout=30.0, federate=True,
                 telemetry_interval_s=2.0, reconnect_window_s=2.0,
                 reconnect_grace_s=4.0, park_ttl_s=60.0,
                 journal_cap=256, _adopt=None, **kw):
        if not isinstance(spec, ModelSpec):
            raise TypeError(
                f"DistFleet needs a ModelSpec (the worker's model "
                f"recipe — serve/dist/worker.py gpt2_spec), got "
                f"{type(spec).__name__}: a live model object cannot "
                f"cross the process boundary")
        if spawn not in ("thread", "process"):
            raise ValueError(
                f"spawn must be 'thread' or 'process', got {spawn!r}")
        for k in ("tp", "ep", "pp"):
            if kw.get(k) not in (None, False):
                raise ValueError(
                    f"{k}= is not supported across the process "
                    f"boundary yet: sharded replicas pin local device "
                    f"groups (run those under ServeFleet)")
        self._spec = spec
        self._spawn_mode = spawn
        self.stream_ships = bool(stream_ships)
        self._rpc_timeout = float(rpc_timeout)
        self._hb_timeout = float(heartbeat_timeout)
        # -- controller survivability ---------------------------------
        self._reconnect_window = float(reconnect_window_s)
        self._reconnect_grace = float(reconnect_grace_s)
        self._park_ttl = float(park_ttl_s)
        self._journal_cap = int(journal_cap)
        self._resume_pool = {}    # idx -> (RESUME frame, Conn) parked
        self._pending_clock_resync = set()
        self._adopt_src = _adopt
        self._adopting = _adopt is not None
        self.adoption = None      # reconciliation report (adopt only)
        if _adopt is None:
            self._token = os.urandom(16)
            self._listener = Listener(token=self._token)
            #: fencing epoch every frame to the workers is stamped
            #: with; an adopting successor bumps it and the workers
            #: refuse this controller's frames typed from then on
            self._epoch = 1
        else:
            a_host, a_port, a_token = _adopt
            self._token = a_token
            self._listener = Listener(host=a_host, port=a_port,
                                      token=a_token)
            self._epoch = None    # negotiated from the workers' offers
        self._graveyard = []
        self._dist_registered = []
        self._ship_streams = {}   # rid -> (dst RemoteSupervisor, ship_id)
        #: completed-ship wall seconds, windowed (the warm-TTFT
        #: evidence surface: snapshot()["dist"]["ship_s_*"])
        self.ship_window = WindowRing(
            kind="event", clock=kw.get("clock", time.monotonic))
        # -- federation state (must exist BEFORE super().__init__:
        # supervisors spawn in there and register their hosts) -------
        self._federate = bool(federate)
        self._telemetry_interval = float(telemetry_interval_s)
        self._t_last_pull = None
        self._ship_hidden = {}    # rid -> wire s overlapped w/ prefill
        self._peer_metrics = {}   # idx -> [Conn transport metrics]
        #: controller-side merge of every worker's telemetry: clocks,
        #: registries, ledgers, traces (observe.federate)
        self.telemetry = FleetTelemetry(
            clock=kw.get("clock", time.monotonic))
        if self._federate:
            # hop records gain a host id so cross-host why_slow and
            # flow arrows can name hosts; module-level install makes
            # health_report()["serve"]["dist"] see THIS fleet
            _reqs.set_host_namer(lambda i: f"w{i}")
            _federate.install(self.telemetry)
        super().__init__(spec, replicas=replicas, **kw)
        self.telemetry.fleet = self.fleet_label
        lblf = dict(fleet=self.fleet_label)
        self._c_ship_hidden = self._reg.counter(
            "serve.dist.ship_wire_hidden_s",
            help="streamed-ship wire seconds overlapped with source "
                 "prefill compute (the hidden half)", **lblf)
        self._c_ship_exposed = self._reg.counter(
            "serve.dist.ship_wire_exposed_s",
            help="ship completion wall seconds on the request's "
                 "critical path (export+commit+land)", **lblf)
        self._c_reconnects = self._reg.counter(
            "serve.dist.reconnects",
            help="worker sessions resumed after a socket loss "
                 "(reconnect window hits — each one is a failover "
                 "plus respawn that did NOT happen)", **lblf)
        self._c_resumed = self._reg.counter(
            "serve.dist.resumed_calls",
            help="unacked CALLs replayed across a resumed session "
                 "(exactly-once: the worker's reply cache dedupes)",
            **lblf)
        self._c_parked = self._reg.counter(
            "serve.dist.parked_results",
            help="journaled terminal results claimed from workers at "
                 "adoption and re-delivered exactly once", **lblf)
        self._g_epoch = self._reg.gauge(
            "serve.dist.epoch",
            help="this controller's fencing epoch (workers refuse "
                 "frames from any lower epoch typed)", **lblf)
        self._g_epoch.set(self._epoch)
        self._dist_registered += [self._c_ship_hidden,
                                  self._c_ship_exposed,
                                  self._c_reconnects, self._c_resumed,
                                  self._c_parked, self._g_epoch]
        if self._adopting:
            self._adopting = False
            self.adoption = self._reconcile_adoption()

    # -- replica construction / teardown ---------------------------------
    def _new_supervisor(self, idx):
        if self._adopting:
            # adoption path: the worker is already alive and built —
            # attach to its redial instead of spawning
            return self._adopt_supervisor(idx)
        proc = self._spawn_worker(idx)
        widx, conn = self._listener.accept_worker(
            timeout=self._init_timeout())
        if widx != idx:
            conn.close()
            raise TransportError(
                f"worker handshake says replica {widx}, expected "
                f"{idx}")
        conn.epoch = self._epoch
        sup_kw = {k: v for k, v in self._sup_kw.items()
                  if k != "clock"}  # callables don't ship; the worker
        #                             keeps its own monotonic clock
        init = {"spec": self._spec, "sup_kw": sup_kw,
                "engine_kw": self._replica_kw(idx),
                "epoch": self._epoch,
                "recover": {"park_ttl": self._park_ttl,
                            "journal_cap": self._journal_cap}}
        if self._federate and self._spawn_mode == "process":
            # the worker process records its OWN ledger + trace and
            # ships them on telemetry pulls; thread mode must NOT —
            # its observe globals are the controller's (shared)
            init["federate"] = {"ledger": True, "trace": True,
                                "stepprof": True, "capacity": 4096}
        ack = conn.call("init", init, timeout=self._init_timeout())
        if not ack["ok"]:
            conn.close()
            raise load_exc(ack["err"])
        sup = RemoteSupervisor(self, idx, conn, proc, ack["value"])
        self._register_host(idx, sup)
        return sup

    def _adopt_supervisor(self, idx):
        """Attach to a LIVE worker orphaned by a dead controller: wait
        for its redial, negotiate the fencing epoch one past the
        highest offer (the dead controller — and anything replaying
        its frames — is refused typed from this moment), and size the
        proxy from a ``describe`` probe instead of an INIT build.
        ``recompiles: 0`` falls out of this: the worker's engine and
        jit caches are never touched."""
        deadline = time.monotonic() + self._init_timeout()
        got = self._accept_resume(idx, deadline)
        if got is None:
            raise PeerTimeoutError(
                f"no RESUME redial from worker r{idx} within the "
                f"adoption window", started=None)
        frame, conn = got
        offered = int(frame.get("epoch", 0))
        if self._epoch is None or offered >= self._epoch:
            self._epoch = offered + 1
        conn.send(MSG_RESUME, {"ok": True, "epoch": self._epoch})
        conn.epoch = self._epoch
        # continue the worker's seq space: its reply cache and journal
        # acks are keyed by it
        conn._seq = int(frame.get("last_seq", 0))
        ack = conn.call("describe", timeout=self._init_timeout())
        if not ack["ok"]:
            conn.close()
            raise load_exc(ack["err"])
        sup = RemoteSupervisor(self, idx, conn, None, ack["value"])
        self._register_host(idx, sup)
        return sup

    def _register_host(self, idx, sup):
        """Federation side of a (re)spawned worker: fresh per-peer
        transport metrics (a replaced peer's series leave the registry
        first — replace_dead must not resurrect the dead conn's
        counts), a fresh NTP-style clock estimate (process mode: new
        process, new clock base), and a fresh telemetry host slot."""
        old = self._peer_metrics.pop(idx, None)
        if old:
            self._reg.remove(*old)
            self._dist_registered = [
                m for m in self._dist_registered if m not in old]
        ms = sup._conn.attach_metrics(self._reg, peer=f"w{idx}")
        self._peer_metrics[idx] = ms
        self._dist_registered += ms
        if not self._federate:
            return
        cs = None
        if self._spawn_mode == "process":
            cs = ClockSync(clock=self._clock)
            try:
                cs.sample(lambda: sup._conn.call(
                    "clock", timeout=10.0,
                    fault_site="serve.dist.telemetry")["value"]["t"])
            except Exception:
                cs = None  # clock probe lost: merge uncorrected
        self.telemetry.host_online(
            f"w{idx}", clock_sync=cs,
            thread=(f"dist-worker-{idx}"
                    if self._spawn_mode == "thread" else None),
            pid=sup.pid)

    def _init_timeout(self) -> float:
        # a spawned process imports jax and compiles from cold; a
        # thread shares this process's caches
        return 300.0 if self._spawn_mode == "process" else 120.0

    def _spawn_worker(self, idx):
        args = (self._listener.host, self._listener.port,
                self._token, idx)
        if self._spawn_mode == "process":
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
            proc = ctx.Process(target=worker_main, args=args,
                               daemon=True, name=f"dist-worker-{idx}")
            proc.start()
            return proc
        t = threading.Thread(target=worker_main, args=args,
                             daemon=True, name=f"dist-worker-{idx}")
        t.start()
        return t

    def kill_worker(self, idx):
        """Chaos/test hook: make replica ``idx``'s worker DIE without
        telling the fleet — process mode kills the process, thread
        mode tells the worker loop to stop (a one-way ``die``) before
        severing the socket, so the worker does NOT redial: a killed
        worker must stay dead (contrast :meth:`blip_worker`).  The
        next RPC to it raises :class:`PeerGoneError` and the normal
        failover path takes over once the reconnect window drains."""
        sup = self._replicas[idx].sup
        proc = sup._proc
        if self._spawn_mode == "process" \
                and hasattr(proc, "terminate"):
            proc.terminate()
            proc.join(timeout=10.0)
        else:
            try:
                # TCP ordering lands the die ahead of the FIN, so the
                # worker stops instead of entering its redial loop
                sup._conn.send_oneway("die")
            except PeerGoneError:
                pass
            sup._conn.close()

    def blip_worker(self, idx):
        """Chaos/test hook: sever the controller-side socket WITHOUT
        telling the worker anything — a modeled transient network
        blip.  The worker's recv fails, it redials with full-jitter
        backoff, and the session resumes inside the reconnect window:
        no failover, no respawn, no cold KV arena."""
        self._replicas[idx].sup._conn.close()

    def crash(self):
        """Chaos/test hook: die the way a crashed controller process
        dies — no shutdown RPCs, no engine closes, no drains.  Workers
        keep stepping live work, journal finished results, and redial;
        a successor attaches to them with :meth:`adopt`.  This fleet
        object is unusable afterwards (its registry entries and
        federation hooks are released so the successor can install
        its own)."""
        self._listener.close()
        for rep in self._replicas:
            rep.sup.engine._closed = True
            try:
                rep.sup._conn.close()
            except Exception:
                pass
        self._closed = True
        self._reg.remove(*self._registered)
        self._reg.remove(*self._dist_registered)
        self._dist_registered = []
        self._peer_metrics = {}
        self._teardown_federation()

    # -- reconnect-with-resume -------------------------------------------
    def _accept_resume(self, idx, deadline):
        """Accept redials until worker ``idx``'s RESUME arrives (or
        the deadline does).  Other workers' resumes landing first are
        parked in the resume pool — with several replicas blipped at
        once, whichever redials first must not be dropped on the
        floor while we wait for a specific one."""
        got = self._resume_pool.pop(idx, None)
        if got is not None:
            return got
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                kind, frame, conn = self._listener.accept_any(
                    timeout=remaining)
            except PeerTimeoutError:
                return None
            except (TransportError, PeerGoneError):
                continue  # a refused handshake does not end the window
            if kind != MSG_RESUME:
                conn.close()  # a fresh HELLO here is a stray spawn
                continue
            widx = int(frame["idx"])
            if widx == idx:
                return frame, conn
            old = self._resume_pool.pop(widx, None)
            if old is not None:
                old[1].close()
            self._resume_pool[widx] = (frame, conn)

    def _resume_peer(self, sup):
        """Controller half of reconnect-with-resume: hold replica
        ``sup._idx`` inside its reconnect window, accept the worker's
        redial, verify the fence, and swap the session onto the new
        socket (seq space and the pending CALL carry over).  Returns
        the worker's RESUME frame, or None when the window closes
        (callers then escalate to the normal PeerGone failover).
        While the window — and the grace period after it — runs, the
        replica's ``reconnect_deadline`` gates the autoscaler's
        ``_replace_dead`` so a blipped worker is never concurrently
        respawned."""
        idx = sup._idx
        rep = (self._replicas[idx]
               if idx < len(self._replicas) else None)
        now = time.monotonic()
        if rep is not None:
            rep.reconnect_deadline = now + max(
                self._reconnect_window, self._reconnect_grace)
        got = self._accept_resume(idx, now + self._reconnect_window)
        if got is None:
            return None
        frame, conn = got
        if int(frame.get("epoch", 0)) > self._epoch:
            # the worker is fenced HIGHER than us: a successor already
            # adopted the fleet and THIS controller is the stale side
            # of the split brain — refuse the session and fail typed
            conn.close()
            raise StaleEpochError(
                f"worker r{idx} is fenced at epoch {frame['epoch']}, "
                f"this controller at {self._epoch}: a successor "
                f"adopted the fleet; this controller is stale")
        conn.send(MSG_RESUME, {"ok": True, "epoch": self._epoch})
        old = sup._conn
        conn.label = old.label or f"r{idx}"
        conn.epoch = self._epoch
        # carry the session: the seq space continues (the worker's
        # reply cache and journal acks key off it) and the unacked
        # pending CALL crosses to the new socket for replay
        conn._seq = max(old._seq, int(frame.get("last_seq", 0)))
        conn._pending = old._pending
        sup._conn = conn
        try:
            old.close()
        except Exception:
            pass
        self._c_reconnects.inc()
        self._after_resume(sup)
        if rep is not None:
            rep.reconnect_deadline = None
        return frame

    def _after_resume(self, sup):
        """Federation bookkeeping for a resumed session: the old
        socket's transport series are retired for fresh ones (same
        retire-unregisters contract as replace_dead), and process-mode
        clock sync re-estimates — deferred to the watchdog while a
        replay is still pending, because an interleaved clock RPC
        would corrupt the replayed call's seq space."""
        idx = sup._idx
        old = self._peer_metrics.pop(idx, None)
        if old:
            self._reg.remove(*old)
            self._dist_registered = [
                m for m in self._dist_registered if m not in old]
        ms = sup._conn.attach_metrics(self._reg, peer=f"w{idx}")
        self._peer_metrics[idx] = ms
        self._dist_registered += ms
        if not self._federate or self._spawn_mode != "process":
            return
        if sup._conn._pending is None:
            self._clock_resync(sup)
        else:
            self._pending_clock_resync.add(idx)

    def _clock_resync(self, sup):
        """Fresh NTP-style offset estimate after a reconnect: the
        worker process kept its clock base, but the blip may have been
        a host stall — re-measuring keeps federated timestamps
        honest."""
        cs = ClockSync(clock=self._clock)
        try:
            cs.sample(lambda: sup._conn.call(
                "clock", timeout=10.0,
                fault_site="serve.dist.telemetry")["value"]["t"])
        except Exception:
            cs = None
        h = self.telemetry.hosts.get(f"w{sup._idx}")
        if h is not None:
            h.clock = cs

    # -- fenced adoption --------------------------------------------------
    @classmethod
    def adopt(cls, spec, port, token, host="127.0.0.1", replicas=2,
              **kw):
        """Attach a NEW controller to live workers orphaned by a dead
        one.  Binds the dead controller's listener address, accepts
        each worker's RESUME redial, bumps the fencing epoch (the dead
        controller — or anything replaying its frames — is refused
        typed on EVERY op from that moment: split-brain routing is
        impossible by construction), reconciles the workers' request
        journals, and resumes routing against engines that were never
        rebuilt — jit caches warm, ``recompiles: 0``.

        The reconciliation report lands on ``fleet.adoption``::

            {"resumed":   {rid: RequestHandle},  # still decoding
             "delivered": {rid: RequestHandle},  # parked result,
                                                 #  re-delivered once
             "requeued":  {rid: RequestHandle},  # never started,
                                                 #  resubmitted in
                                                 #  arrival order
             "rejected":  {rid: error}}          # started-and-dead /
                                                 #  TTL-expired: typed
        """
        return cls(spec, replicas=replicas,
                   _adopt=(host, port, token), **kw)

    def _note_adopt_hop(self, rid, req, idx, kind):
        """Ledger: adoption is a routing hop (``via=adopt``).  Process
        mode opens a minimal entry first — the successor's ledger
        never saw the original submit (it happened in a dead
        process); thread mode shares the predecessor's globals, so
        the original entry is already there."""
        if not _reqs._active:
            return
        if self._spawn_mode == "process":
            _reqs._ledger.on_submit(
                rid,
                engine=self._replicas[idx].sup.engine.stats
                .engine_label,
                t=self._clock(), prompt_len=len(req.prompt_ids),
                max_new_tokens=req.max_new_tokens)
        _reqs._ledger.annotate_hop(rid, replica=idx, via="adopt",
                                   adopt=kind)

    def _reconcile_adoption(self) -> dict:
        """Merge every worker's journal into one fleet-wide verdict,
        processed in original arrival order: live work re-attaches
        (the worker kept decoding the whole time), parked terminal
        results are claimed and re-delivered exactly once, work that
        never started is resubmitted through normal admission, and
        anything unrecoverable (TTL-expired, started on a dead
        engine) is refused typed — never silently re-run, because a
        replay after delivered tokens could duplicate them."""
        report = {"resumed": {}, "delivered": {}, "requeued": {},
                  "rejected": {}}
        entries = []
        for rep in self._replicas:
            inv = rep.sup._rpc("reconcile")
            for rid, ent in inv["requests"].items():
                entries.append((int(ent["order"]), rep.idx, rid, ent))
        entries.sort(key=lambda t: (t[0], t[1]))
        for _order, idx, rid, ent in entries:
            sup = self._replicas[idx].sup
            st = ent["state"]
            if st == "live":
                req = load_request(ent["req"], clock=self._clock)
                inner = RequestHandle(req)
                sup._inner[rid] = inner
                sup._order.append(rid)
                if ent.get("cursor", 0) > 0:
                    # tokens already streamed (to the dead
                    # controller): NOT safely re-runnable — pin the
                    # delivery-started verdict for any later failover
                    sup._streamed.add(rid)
                handle = RequestHandle(req)
                route = _Route(handle, self.step_count)
                route.attempts.append((idx, inner))
                self._routes[rid] = route
                self._order.append(rid)
                self._note_adopt_hop(rid, req, idx, "resumed")
                report["resumed"][rid] = handle
                continue
            if st == "parked":
                out = sup._rpc("claim", {"rid": rid})
                if out.get("status") == "parked":
                    self._c_parked.inc()
                    payload = out["out"]
                    req_d = out.get("req")
                    if "result" in payload:
                        req = load_request(req_d, clock=self._clock)
                        handle = RequestHandle(req)
                        handle._finish(
                            sup._load_result(payload["result"]))
                        self._note_adopt_hop(rid, req, idx,
                                             "delivered")
                        report["delivered"][rid] = handle
                        continue
                    err = load_exc(payload["err"])
                    if getattr(err, "started", None) is False \
                            and req_d is not None:
                        # rejected without ever occupying a slot
                        # (e.g. the engine died while it sat queued):
                        # same seed -> same chain -> safe to requeue
                        req = load_request(req_d, clock=self._clock)
                        try:
                            handle = self.submit(req)
                        except Exception as e:
                            report["rejected"][rid] = e
                            continue
                        if _reqs._active:
                            _reqs._ledger.annotate_hop(
                                rid, via="adopt", adopt="requeued")
                        report["requeued"][rid] = handle
                        continue
                    report["rejected"][rid] = err
                    if _reqs._active:
                        _reqs._ledger.on_reject(
                            rid, t=self._clock(),
                            reason="adopt_dead",
                            started=getattr(err, "started", None))
                    continue
                st = out.get("status") or "gone"
            # expired / gone: the terminal result is unrecoverable and
            # the cursor says whether tokens ever streamed — refuse
            # typed rather than risk duplicating delivered tokens
            cursor = int(ent.get("cursor", 0))
            err = EngineFailedError(
                f"{rid}: unrecoverable across controller adoption "
                f"({st}, cursor={cursor})", request_id=rid,
                started=(True if cursor > 0 else None))
            report["rejected"][rid] = err
            if _reqs._active:
                _reqs._ledger.on_reject(
                    rid, t=self._clock(), reason=f"adopt_{st}",
                    started=err.started)
        self._g_epoch.set(self._epoch)
        return report

    def _reap(self):
        """Join/terminate every worker handed to the graveyard (and
        any still attached)."""
        procs, self._graveyard = self._graveyard, []
        for p in procs:
            if hasattr(p, "terminate"):   # a process
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
                    p.join(timeout=5.0)
                    if p.is_alive():
                        p.kill()
                        p.join(timeout=5.0)
            else:                          # a thread
                p.join(timeout=5.0)

    def retire_replica(self, idx):
        """Scale-down retire, federation side: the worker's per-peer
        transport series and its telemetry host slot leave with it —
        a retired host must not freeze into the federated exposition
        (the dist analogue of ``EngineStats.unregister``)."""
        super().retire_replica(idx)
        self._unregister_host(idx)

    def _unregister_host(self, idx):
        ms = self._peer_metrics.pop(idx, None)
        if ms:
            self._reg.remove(*ms)
            self._dist_registered = [
                m for m in self._dist_registered if m not in ms]
        if self._federate:
            self.telemetry.remove_host(f"w{idx}")

    def _teardown_federation(self):
        if self._federate:
            _reqs.set_host_namer(None)
            _federate.uninstall(self.telemetry)

    def close(self):
        was_closed = self._closed
        super().close()
        if not was_closed:
            self._listener.close()
            self._reap()
            self._reg.remove(*self._dist_registered)
            self._dist_registered = []
            self._peer_metrics = {}
            self._teardown_federation()

    def __exit__(self, exc_type, *a):
        closed_before = self._closed
        r = super().__exit__(exc_type, *a)
        if not closed_before and exc_type is not None:
            self._listener.close()
            self._reap()
            self._reg.remove(*self._dist_registered)
            self._dist_registered = []
            self._peer_metrics = {}
            self._teardown_federation()
        return r

    # -- drive: overlapped stepping, ping-based watchdog -----------------
    def _step_replicas(self):
        """Issue EVERY healthy replica's step RPC, then collect: the
        workers decode concurrently and the fleet pays one round-trip
        latency per step, not one per replica."""
        started = []
        for rep in self._replicas:
            if not rep.healthy or not rep.sup.pending:
                continue
            try:
                started.append((rep, rep.sup.step_begin()))
            except RestartBudgetExceededError as e:
                self._mark_down(rep, e)
        for rep, seq in started:
            try:
                rep.sup.step_finish(seq)
            except RestartBudgetExceededError as e:
                self._mark_down(rep, e)

    def _check_watchdog(self):
        """Per-peer liveness: heartbeats are piggybacked on every
        received frame, so only QUIET peers are pinged — a peer that
        answers nothing within the heartbeat window is gone."""
        for rep in self._replicas:
            if not rep.healthy:
                continue
            sup = rep.sup
            if sup._conn.age() < self._hb_timeout:
                continue
            try:
                sup.ping()
            except RestartBudgetExceededError as e:
                self._mark_down(rep, e)
        # deferred post-resume clock re-estimates: safe now if the
        # replayed CALL has been answered (no pending seq to corrupt)
        for idx in list(self._pending_clock_resync):
            rep = self._replicas[idx]
            if rep.healthy and rep.sup._conn._pending is None:
                self._pending_clock_resync.discard(idx)
                self._clock_resync(rep.sup)
        self._maybe_pull_telemetry()

    def _maybe_pull_telemetry(self, force=False):
        """Periodic (or forced on-demand) telemetry pull from every
        healthy worker.  Rides its OWN fault site
        (``serve.dist.telemetry``) so chaos tests partitioning the
        control plane never have their injected fault consumed by a
        background pull.  ANY failure degrades that host to a typed
        ``stale`` marker — telemetry loss never raises into the step
        loop and never blocks serving."""
        if not self._federate:
            return
        now = self._clock()
        if not force and self._t_last_pull is not None \
                and now - self._t_last_pull < self._telemetry_interval:
            return
        self._t_last_pull = now
        process = self._spawn_mode == "process"
        for rep in self._replicas:
            host = f"w{rep.idx}"
            if rep.retired or host not in self.telemetry.hosts:
                continue
            if not rep.healthy:
                self.telemetry.mark_stale(host, "replica down")
                continue
            try:
                # thread mode shares this process's observe globals —
                # pull nothing but liveness (registry/ledger/trace are
                # already visible locally); process mode drains the
                # worker's private copies across the wire
                msg = rep.sup._conn.call(
                    "telemetry",
                    {"registry": process, "ledger": process,
                     "trace": process, "drain": process},
                    timeout=10.0, fault_site="serve.dist.telemetry")
                if not msg["ok"]:
                    raise load_exc(msg["err"])
                self.telemetry.ingest(host, msg["value"], t=now)
            except Exception as e:
                self.telemetry.mark_stale(host, repr(e))

    # -- streamed KV shipping --------------------------------------------
    def _before_build_advance(self, sjob):
        """Open the streamed ship on a build's first advance: pick the
        destination NOW (the same prefix-hash-sticky candidate order
        the bulk path uses), start its staging, and register the frame
        sink — every lane the coming chunks complete ships while the
        source still prefills."""
        if not self.stream_ships or sjob.rid in self._ship_streams:
            return
        job = sjob.job
        if getattr(job, "stream_meta", None) is None:
            return  # resident hit or non-remote job: bulk path
        for idx in self._ship_dsts(sjob.request):
            dst_sup = self._replicas[idx].sup
            ship_id = f"s{next(_ship_ids)}-{sjob.rid}"
            try:
                dst_sup.ship_begin(ship_id, job.stream_meta)
            except PeerGoneError as e:
                self._mark_down(self._replicas[idx], e)
                continue
            self._ship_streams[sjob.rid] = (dst_sup, ship_id)
            return

    def _complete_ship(self, sjob, src_rep):
        stream = self._ship_streams.get(sjob.rid)
        if stream is None:
            return super()._complete_ship(sjob, src_rep)
        dst_sup, ship_id = stream
        req = sjob.request
        t0 = self._clock()
        try:
            meta, resident = src_rep.sup.export_ship_meta(sjob.job)
        finally:
            sjob.job = None
        n = meta["n_data"]
        if resident:
            self._prefix_index.register(req.prompt_ids, n,
                                        src_rep.idx)
        dst_rep = self._replicas[dst_sup._idx]
        if not dst_rep.healthy or dst_rep.sup is not dst_sup:
            self._ship_fallback(sjob, "stream_dst_lost")
            return
        try:
            path = dst_sup.ship_commit(ship_id, req.prompt_ids, meta)
        except RestartBudgetExceededError as e:
            self._mark_down(dst_rep, e)
            self._ship_fallback(sjob, "stream_dst_lost")
            return
        except KVImageError as e:
            # half-shipped or corrupted staging failed the typed
            # validation at admit: recompute cold, never a wrong token
            self._log.warning(
                "streamed ship for %s rejected at commit (%r); "
                "serving cold", sjob.rid, e)
            self._ship_fallback(sjob, "half_shipped")
            return
        self._ship_streams.pop(sjob.rid, None)
        if path is None:
            self._ship_fallback(sjob, "dst_capacity")
            return
        self._land_shipped(sjob, src_rep, dst_rep, path, n,
                           meta["nbytes"], t0)

    def _land_shipped(self, sjob, src_rep, dst_rep, path, n, nbytes,
                      t0):
        exposed = self._clock() - t0
        self.ship_window.append(exposed)
        hidden = self._ship_hidden.pop(sjob.rid, 0.0)
        self._c_ship_hidden.inc(hidden)
        self._c_ship_exposed.inc(exposed)
        return super()._land_shipped(sjob, src_rep, dst_rep, path, n,
                                     nbytes, t0)

    def _abandon_build(self, sjob):
        stream = self._ship_streams.pop(sjob.rid, None)
        self._ship_hidden.pop(sjob.rid, None)
        if stream is not None:
            dst_sup, ship_id = stream
            dst_sup.ship_abort(ship_id)  # frees the staging buffers
        super()._abandon_build(sjob)

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["dist"] = {
            "spawn": self._spawn_mode,
            "stream_ships": self.stream_ships,
            "rpcs": sum(c.value for c in self._dist_registered
                        if c.name == "serve.dist.rpcs"),
            "rpc_errors": sum(c.value for c in self._dist_registered
                              if c.name == "serve.dist.rpc_errors"),
            "frames": sum(c.value for c in self._dist_registered
                          if c.name == "serve.dist.frames"),
            "frame_bytes": sum(
                c.value for c in self._dist_registered
                if c.name == "serve.dist.frame_bytes"),
            "ship_s_mean": self.ship_window.mean(300.0),
            "ship_s_p95": self.ship_window.quantile(0.95, 300.0),
            "retries": sum(c.value for c in self._dist_registered
                           if c.name == "serve.dist.retries"),
            "reconnects": self._c_reconnects.value,
            "resumed_calls": self._c_resumed.value,
            "parked_results": self._c_parked.value,
            "epoch": self._epoch,
            "ship_wire_hidden_s": self._c_ship_hidden.value,
            "ship_wire_exposed_s": self._c_ship_exposed.value,
            "ship_overlap_efficiency": self._ship_overlap(),
            "telemetry": {
                h.host: {"stale": h.stale,
                         "stale_reason": h.stale_reason,
                         "pulls": h.pulls}
                for h in self.telemetry.hosts.values()
            } if self._federate else None,
        }
        return snap

    def _ship_overlap(self):
        """Fraction of streamed-ship wire time hidden behind source
        prefill: hidden / (hidden + exposed).  None until a streamed
        ship lands."""
        hidden = self._c_ship_hidden.value
        exposed = self._c_ship_exposed.value
        total = hidden + exposed
        return (hidden / total) if total > 0 else None
