"""Replica worker: one supervised serve engine behind the framed RPC
loop.

A worker is ONE replica of a :class:`~singa_tpu.serve.dist.DistFleet`
living in its own process (``multiprocessing`` spawn for tests/CI —
or, degenerately, a thread: same sockets, same framing, same
serialization, minus process isolation, which is what keeps the tier-1
tests fast).  It dials back to the fleet's listener, handshakes, then
serves a strictly serial command loop: every fleet-side
``RemoteSupervisor`` call is one ``CALL`` frame here, dispatched to
the REAL :class:`~singa_tpu.serve.supervisor.EngineSupervisor` the
worker hosts.  Exceptions cross the wire as typed descriptions —
``EngineFailedError.started`` survives serialization, because the
fleet's requeue-safety decision hangs on it.

The worker builds its model from a :class:`ModelSpec` shipped in the
INIT call: an importable factory plus the fleet's weight state dict
(numpy), so worker weights are BYTE-IDENTICAL to the fleet's and token
streams match the single-process fleet exactly (two independently
initialized models would not — parameter init is random).

Streamed KV shipping: a ship build advancing here returns, with each
``build_advance`` reply, the newly completed lanes of the canonical
chunk row sliced PER LAYER (``(leaf, layer, lane_lo, lane_hi,
bytes)``).  Canonical prefill KV is append-only and invariant — the
warm==cold pin's foundation — so lanes copied out mid-build are
byte-equal to the final exported image's slices, and the destination
can stage them while the source is still prefilling later chunks.
The destination half (``ship_begin``/``ship_frame`` one-ways, then a
``ship_commit`` call) assembles the staged slices, seals them into a
:class:`~singa_tpu.serve.kvimage.KVImage` with the source's pack-time
header and crc32, and admits through the same typed validation as any
other image: a missing or corrupted frame is a checksum mismatch —
cold fallback, never a wrong token.
"""

from __future__ import annotations

import itertools
import os
import random
import time

import numpy as np

from .transport import (PeerGoneError, StaleEpochError, TransportError,
                        _full_jitter, connect_worker, resume_worker)
from .transport import MSG_CALL, MSG_ONEWAY, MSG_REPLY
from ..kvimage import KVIMAGE_VERSION, KVImage, KVImageError, leaf_list
from ..request import (DeadlineExceededError, EngineFailedError,
                       FleetDownError, GenerationRequest, LoadShedError,
                       QueueFullError, RestartBudgetExceededError)

__all__ = ["ModelSpec", "gpt2_factory", "gpt2_spec", "worker_main"]


# -- error / request / result wire forms --------------------------------
#: typed errors that reconstruct to their own class on the fleet side;
#: anything else degrades to RuntimeError with the original repr
_ERR_TYPES = {
    c.__name__: c for c in (
        QueueFullError, DeadlineExceededError, EngineFailedError,
        RestartBudgetExceededError, FleetDownError, LoadShedError,
        KVImageError, StaleEpochError, ValueError, RuntimeError)}


def dump_exc(e) -> dict:
    return {"type": type(e).__name__, "msg": str(e),
            "request_id": getattr(e, "request_id", None),
            "started": getattr(e, "started", None),
            "engine_step": getattr(e, "engine_step", None)}


def load_exc(d):
    cls = _ERR_TYPES.get(d["type"])
    if cls is None:
        return RuntimeError(f"[worker {d['type']}] {d['msg']}")
    if issubclass(cls, EngineFailedError):
        return cls(d["msg"], request_id=d.get("request_id"),
                   started=d.get("started"),
                   engine_step=d.get("engine_step"))
    return cls(d["msg"])


def dump_request(req, clock) -> dict:
    """Request fields a worker rebuilds a GenerationRequest from.
    ``deadline`` is absolute on the SENDER's clock — it crosses the
    wire as a remaining-time delta and re-anchors on the worker's
    clock (the two processes share no clock base)."""
    return {
        "prompt_ids": np.asarray(req.prompt_ids, np.int32),
        "max_new_tokens": req.max_new_tokens,
        "temperature": req.temperature, "seed": req.seed,
        "deadline_rel": (None if req.deadline is None
                         else req.deadline - clock()),
        "priority": req.priority, "pin_session": req.pin_session,
        "stop_token": req.stop_token, "request_id": req.request_id,
        "stream": req.on_token is not None,
    }


def load_request(d, on_token=None, clock=time.monotonic):
    return GenerationRequest(
        prompt_ids=d["prompt_ids"],
        max_new_tokens=d["max_new_tokens"],
        temperature=d["temperature"], seed=d["seed"],
        deadline=(None if d["deadline_rel"] is None
                  else clock() + d["deadline_rel"]),
        on_token=on_token, priority=d["priority"],
        pin_session=d["pin_session"], stop_token=d["stop_token"],
        request_id=d["request_id"])


class ModelSpec:
    """Picklable recipe for the worker's model: an importable
    ``factory(**factory_kw)`` returning an UNcompiled model, the
    compile probe length, and the weight state dict (numpy) captured
    from the fleet-side model — shipping states is what makes worker
    weights byte-identical to the fleet's."""

    def __init__(self, factory, factory_kw=None, states=None,
                 compile_len=16):
        self.factory = factory
        self.factory_kw = dict(factory_kw or {})
        self.states = states
        self.compile_len = int(compile_len)

    def build(self):
        from ... import tensor

        m = self.factory(**self.factory_kw)
        m.compile([tensor.from_numpy(
            np.zeros((1, self.compile_len), np.int32))],
            is_train=False, use_graph=False)
        if self.states:
            m.set_states(self.states)
        return m


def gpt2_factory(cfg):
    from ...models.gpt2 import GPT2LMHead

    return GPT2LMHead(cfg)


def gpt2_spec(model, compile_len=16) -> ModelSpec:
    """Spec for a compiled fleet-side GPT2LMHead: same config, same
    weights."""
    from ... import tensor

    states = {n: tensor.to_numpy(t)
              for n, t in model.get_states().items()}
    return ModelSpec(gpt2_factory, {"cfg": model.cfg}, states,
                     compile_len=compile_len)


# -- the worker loop -----------------------------------------------------
class _Worker:
    def __init__(self, conn, clock=time.monotonic, redial=None):
        self.conn = conn
        self.sup = None
        self._clock = clock
        self._handles = {}     # rid -> (handle, request)
        self._tokens = []      # (rid, token) streamed since last step
        self._jobs = {}        # job_id -> [PrefixJob, lanes_sent]
        self._paths = {}       # path_id -> acquired radix node path
        self._sessions = {}    # sid -> SessionHandle
        self._staged = {}      # ship_id -> (meta, leaf buffers)
        self._ids = itertools.count(1)
        self._stop = False
        self._led = None       # this process's RequestLedger (federate)
        # -- controller-survivability state --------------------------
        #: (host, port, token, idx) to redial on socket loss; None
        #: disables reconnect (legacy / test harness direct conns)
        self._redial = redial
        #: fencing epoch last obeyed — frames stamped with an OLDER
        #: epoch come from a deposed controller and are refused typed
        self._epoch = 0
        #: single-entry reply cache: the strictly serial protocol
        #: means at most ONE reply can be in flight, so caching the
        #: last (seq, reply) gives exactly-once call semantics across
        #: a reconnect — a replayed seq answers from memory without
        #: re-executing
        self._last_seq = 0
        self._last_reply = None
        #: (reply_seq, [rids]) whose terminal results rode the reply
        #: — deleted from the journal once a STRICTLY NEWER call
        #: proves the controller received it (piggybacked ack)
        self._unacked = None
        #: rid -> {state, req, cursor, order, out, t} — the request
        #: journal an adopting controller reconciles against.  States:
        #: live (queued or decoding), resolved (handle done, result
        #: still on the handle), done (result drained into ``out``,
        #: awaiting ack), expired (TTL tombstone)
        self._journal = {}
        self._arrival = itertools.count(1)
        self._park_ttl = 60.0
        self._journal_cap = 256
        self._reconnect_attempts = 20
        self._backoff_base = 0.1
        self._backoff_cap = 2.0
        self._redial_timeout = 5.0
        self._rng = random.Random()

    # engine-side streaming callback: tokens ride the next step reply
    def _on_token(self, req, tok):
        self._tokens.append((req.request_id, int(tok)))
        ent = self._journal.get(req.request_id)
        if ent is not None:
            # the emitted-token cursor: how far this request's stream
            # has progressed — an adopting controller reads it to tell
            # started work (cursor > 0: not safely re-runnable) from
            # never-started
            ent["cursor"] += 1

    @property
    def _eng(self):
        return self.sup.engine

    def _view(self) -> dict:
        eng = self._eng
        if eng._closed or eng._failed:
            return {"queue_depth": 0, "live_slots": 0,
                    "tpot_ewma": None, "blocks_used": None,
                    "cached_blocks": None,
                    "live_rids": [], "restarts": self.sup.restarts}
        arena = eng.paged_arena
        cache = eng.prefix_cache
        return {
            "queue_depth": eng.scheduler.queue_depth,
            "live_slots": eng.live_slots,
            "tpot_ewma": eng.stats.tpot_ewma,
            "blocks_used": (arena.blocks_used
                            if arena is not None else None),
            "cached_blocks": (cache.cached_blocks
                              if cache is not None else None),
            "live_rids": sorted(eng.live_request_ids),
            "restarts": self.sup.restarts,
        }

    def _dump_result(self, res) -> dict:
        d = {"request_id": res.request_id,
             "tokens": np.asarray(res.tokens),
             "finish_reason": res.finish_reason, "ttft": res.ttft,
             "tpot": res.tpot, "queue_time": res.queue_time,
             "admitted_step": res.admitted_step,
             "finished_step": res.finished_step, "session": None}
        if res.session is not None:
            sid = f"s{next(self._ids)}"
            self._sessions[sid] = res.session
            d["session"] = {"sid": sid,
                            "tokens": np.asarray(res.session.tokens)}
        return d

    def _drain_resolved(self) -> dict:
        out = {}
        for rid in list(self._handles):
            h, _req = self._handles[rid]
            if not h.done():
                continue
            del self._handles[rid]
            if h._error is not None:
                out[rid] = {"err": dump_exc(h._error)}
            else:
                out[rid] = {"result": self._dump_result(h._result)}
            ent = self._journal.get(rid)
            if ent is not None:
                # drained into a reply: journal the terminal result
                # until a newer call acks the reply (or the TTL fires)
                ent["state"] = "done"
                ent["out"] = out[rid]
                ent["t"] = self._clock()
        return out

    # -- journal maintenance ---------------------------------------------
    def _stamp_resolved(self):
        """Mark journal entries whose handle finished as ``resolved``.
        The result deliberately STAYS on the handle: if the same
        controller resumes, the next ``op_step``'s normal drain
        delivers it; only an adopting controller claims it out of the
        journal."""
        for rid, (h, _req) in list(self._handles.items()):
            if not h.done():
                continue
            ent = self._journal.get(rid)
            if ent is not None and ent["state"] == "live":
                ent["state"] = "resolved"
                ent["t"] = self._clock()

    def _sweep_journal(self):
        """Expire parked results past their TTL: the result is dropped
        (nobody came back for it) and a tombstone remains so a late
        adopter gets a typed ``expired`` verdict instead of silence."""
        now = self._clock()
        for rid in list(self._journal):
            ent = self._journal[rid]
            if ent["state"] in ("resolved", "done") \
                    and now - ent["t"] > self._park_ttl:
                self._handles.pop(rid, None)
                self._journal[rid] = {
                    "state": "expired", "req": None, "out": None,
                    "cursor": ent["cursor"], "order": ent["order"],
                    "t": now}

    def _trim_journal(self):
        """Bound the journal: evict the oldest non-live entries past
        the cap (live entries are already bounded by the engine's own
        admission control, so eviction always terminates)."""
        while len(self._journal) > self._journal_cap:
            victim = next((rid for rid, ent in self._journal.items()
                           if ent["state"] != "live"), None)
            if victim is None:
                break
            del self._journal[victim]

    # -- op handlers -----------------------------------------------------
    def op_init(self, p):
        from ..supervisor import EngineSupervisor

        model = p["spec"].build()
        self.sup = EngineSupervisor(model, **p["sup_kw"],
                                    **p["engine_kw"])
        # federation flags arrive ONLY in process mode: this process's
        # observe globals are private, so enabling the ledger/trace
        # here cannot clobber the fleet's own (in thread mode they are
        # the SAME globals — the fleet never sends the flags there)
        fed = p.get("federate") or {}
        if fed.get("ledger"):
            from ...observe import requests as _w_reqs
            self._led = _w_reqs.enable(
                capacity=int(fed.get("capacity", 4096)))
        if fed.get("trace"):
            from ...observe import trace as _w_trace
            # align the trace clock with the ledger/probe clock so one
            # per-host offset corrects every shipped timestamp
            _w_trace.enable(clock=self._clock)
        if fed.get("stepprof"):
            from ...observe import stepprof as _w_stepprof
            # per-step host/device anatomy: the profiler's trace
            # records (cat step.host/step.device) ride the trace
            # shipping above, so the controller's merged Chrome trace
            # grows dual per-host step lanes for free; the probe clock
            # keeps them on the same correctable time base
            _w_stepprof.enable(clock=self._clock)
        if "epoch" in p:
            self._epoch = int(p["epoch"])
        rec = p.get("recover") or {}
        self._park_ttl = float(rec.get("park_ttl", self._park_ttl))
        self._journal_cap = int(rec.get("journal_cap",
                                        self._journal_cap))
        self._reconnect_attempts = int(rec.get(
            "attempts", self._reconnect_attempts))
        self._backoff_base = float(rec.get("base",
                                           self._backoff_base))
        self._backoff_cap = float(rec.get("cap", self._backoff_cap))
        return self._ack()

    def _ack(self) -> dict:
        """The engine-description dict the controller sizes its
        RemoteSupervisor from — returned by INIT at first build and by
        ``describe`` when an adopting controller attaches to an
        already-built worker."""
        eng = self.sup.engine
        arena = eng.paged_arena
        return {
            "max_slots": eng.max_slots, "max_len": eng.max_len,
            "budget": eng._budget,
            "engine_label": eng.stats.engine_label,
            "max_queue_depth": int(getattr(
                eng.scheduler, "max_queue_depth", 64) or 64),
            "has_arena": arena is not None,
            "has_cache": eng.prefix_cache is not None,
            "block_size": (arena.block_size
                           if arena is not None else None),
            "num_blocks": (arena.num_blocks
                           if arena is not None else None),
            "quant": arena.quant if arena is not None else None,
            "pid": os.getpid(),
        }

    def op_describe(self, p):
        """Adoption probe: re-describe the live engine to a controller
        that did not build it (and therefore never saw the INIT ack)."""
        return self._ack()

    def op_submit(self, p):
        d = p["request"]
        req = load_request(
            d, on_token=self._on_token if d["stream"] else None,
            clock=self._clock)
        h = self.sup.submit(req)
        self._handles[req.request_id] = (h, req)
        self._journal[req.request_id] = {
            "state": "live", "req": d, "cursor": 0,
            "order": next(self._arrival), "out": None,
            "t": self._clock()}
        self._trim_journal()
        return {"view": self._view()}

    def op_validate(self, p):
        req = load_request(p["request"], clock=self._clock)
        self._eng.validate_request(req)
        return {}

    def op_step(self, p):
        budget = None
        try:
            if self.sup.pending:
                self.sup.step()
        except RestartBudgetExceededError as e:
            budget = dump_exc(e)
        toks, self._tokens = self._tokens, []
        return {"resolved": self._drain_resolved(), "tokens": toks,
                "view": self._view(), "budget": budget}

    def op_abandon(self, p):
        try:
            self.sup.abandon(p.get("reason", "fleet failover"))
        except RestartBudgetExceededError:
            pass
        toks, self._tokens = self._tokens, []
        return {"resolved": self._drain_resolved(), "tokens": toks}

    def op_build_start(self, p):
        job = self.sup.start_prefix_build(p["prompt_ids"])
        if job is None:
            return {"job_id": None}
        jid = f"j{next(self._ids)}"
        self._jobs[jid] = [job, 0]
        meta = None
        if p.get("stream") and not job.hit:
            B = self._eng.paged_arena.block_size
            w = job.n_goal * B
            leaves = leaf_list(job.kc_row) + leaf_list(job.vc_row)
            meta = {
                "k_leaves": len(leaf_list(job.kc_row)),
                "n_data": job.n_goal, "block_size": B,
                "quant": self._eng.paged_arena.quant,
                # narrow staging shapes: lane axis cut to the shipped
                # width (the exported image's exact geometry)
                "leaves": [(tuple(a.shape[:3]) + (w,)
                            + tuple(a.shape[4:]), str(a.dtype))
                           for a in leaves],
            }
        return {"job_id": jid, "hit": job.hit, "n_goal": job.n_goal,
                "stream_meta": meta}

    def _slice_frames(self, job, lo, hi):
        """Per-(leaf, layer) lane slices [lo, hi) of the build row —
        the streamed ship's wire granularity.  Canonical chunk KV is
        append-only, so these bytes equal the final image's."""
        frames = []
        leaves = leaf_list(job.kc_row) + leaf_list(job.vc_row)
        for li, leaf in enumerate(leaves):
            L = leaf.shape[0]
            for layer in range(L):
                arr = np.asarray(leaf[layer:layer + 1, :, :, lo:hi])
                frames.append((li, layer, lo, hi, arr.tobytes()))
        return frames

    def op_build_advance(self, p):
        ent = self._jobs.get(p["job_id"])
        if ent is None:
            return {"status": "rebuilt", "frames": []}
        job, sent = ent
        done = self.sup.advance_prefix_build(job, p["budget"],
                                             rid=p.get("rid"))
        if done is None:
            # the engine died mid-chunk and the supervisor rebuilt it:
            # the job's rows belong to the dead engine — drop it
            del self._jobs[p["job_id"]]
            return {"status": "rebuilt", "frames": []}
        frames = []
        if p.get("stream") and not job.hit:
            B = self._eng.paged_arena.block_size
            hi = min(job.off, job.n_goal * B)
            if hi > sent:
                frames = self._slice_frames(job, sent, hi)
                ent[1] = hi
        return {"status": "done" if done else "more",
                "frames": frames}

    def op_build_export(self, p):
        job, _ = self._jobs.pop(p["job_id"])
        image, resident = self.sup.export_prefix_image(job)
        return {"image": image.to_bytes(), "resident": resident}

    def op_build_export_meta(self, p):
        """Streamed-ship export: the lanes already crossed the wire as
        frames; only the image's identity (header + crc + geometry)
        and the source-residency verdict travel here."""
        job, _ = self._jobs.pop(p["job_id"])
        image, resident = self.sup.export_prefix_image(job)
        return {"meta": {
                    "header": image.header, "checksum": image.checksum,
                    "n_data": image.n_data,
                    "block_size": image.block_size,
                    "quant": image.quant, "nbytes": image.nbytes,
                    "k_leaves": len(leaf_list(image.kc))},
                "resident": resident}

    def op_build_abandon(self, p):
        ent = self._jobs.pop(p["job_id"], None)
        if ent is not None:
            self.sup.abandon_prefix_build(ent[0])
        return {}

    def op_admit_image(self, p):
        image = KVImage.from_bytes(p["image"])
        path = self.sup.admit_prefix_image(p["tokens"], image)
        if path is None:
            return {"path": None}
        pid = f"p{next(self._ids)}"
        self._paths[pid] = path
        return {"path": pid}

    def op_ship_begin(self, p):
        bufs = [np.zeros(shape, dtype)
                for shape, dtype in p["meta"]["leaves"]]
        self._staged[p["ship_id"]] = (p["meta"], bufs)

    def op_ship_frame(self, p):
        ent = self._staged.get(p["ship_id"])
        if ent is None:
            return  # aborted or unknown: drop (commit will fail typed)
        _meta, bufs = ent
        li, layer, lo, hi = p["leaf"], p["layer"], p["lo"], p["hi"]
        dst = bufs[li][layer:layer + 1, :, :, lo:hi]
        dst[...] = np.frombuffer(
            p["bytes"], dtype=bufs[li].dtype).reshape(dst.shape)

    def op_ship_abort(self, p):
        self._staged.pop(p["ship_id"], None)

    def op_ship_commit(self, p):
        ent = self._staged.pop(p["ship_id"], None)
        if ent is None:
            return {"path": None, "reason": "no_staging"}
        meta, bufs = ent
        k = p["k_leaves"]

        def tree(ls):
            return ls[0] if len(ls) == 1 else tuple(ls)

        image = KVImage(KVIMAGE_VERSION, p["block_size"], p["n_data"],
                        p["quant"], p["header"], tree(bufs[:k]),
                        tree(bufs[k:]), checksum=p["checksum"])
        # admit runs the full typed validation (geometry + header +
        # crc32): a half-shipped or bit-flipped staging fails HERE and
        # the fleet replays the request cold — never a wrong token
        path = self.sup.admit_prefix_image(p["tokens"], image)
        if path is None:
            return {"path": None, "reason": "capacity"}
        pid = f"p{next(self._ids)}"
        self._paths[pid] = path
        return {"path": pid}

    def op_prefix_lookup(self, p):
        eng = self._eng
        if (eng._closed or eng._failed
                or eng.prefix_cache is None):
            return {"n": 0}
        return {"n": len(eng.prefix_cache.lookup(p["tokens"]))}

    def op_cache_release(self, p):
        path = self._paths.pop(p["path"], None)
        if path is not None:
            try:
                self._eng.prefix_cache.release(path)
            except (RuntimeError, AttributeError):
                pass  # engine rebuilt under the pin: stale path
        return {}

    def op_session_release(self, p):
        sess = self._sessions.pop(p["sid"], None)
        if sess is not None:
            try:
                sess.release()
            except RuntimeError:
                pass
        return {}

    def op_snapshot(self, p):
        return {"stats": self._eng.stats.snapshot()}

    def op_ping(self, p):
        return {}

    def op_clock(self, p):
        """NTP-style probe target: the worker's monotonic now.  The
        fleet brackets this reply with its own clock reads to estimate
        the peer offset (error bounded by RTT/2)."""
        return {"t": self._clock()}

    def op_telemetry(self, p):
        """Telemetry pull: registry dump, sealed ledger entries and
        (optionally drained) trace events, each gated by a request
        flag.  Read-only over observe state — never touches the
        engine, so a pull can never wedge serving."""
        out = {"clock": self._clock(), "pid": os.getpid()}
        if p.get("registry"):
            from ...observe.registry import registry as _w_registry
            out["registry"] = _w_registry().dump()
        if p.get("ledger") and self._led is not None:
            out["ledger"] = self._led.entries()
        if p.get("trace"):
            from ...observe import trace as _w_trace
            if _w_trace.is_enabled():
                out["trace"] = (_w_trace.drain() if p.get("drain")
                                else _w_trace.events())
        if p.get("jit"):
            from ..jitpin import jit_cache_size
            out["jit_cache"] = jit_cache_size()
        return out

    def op_reconcile(self, p):
        """Adoption inventory: per journaled request, its state (live
        / parked / expired), token cursor, arrival order, and — for
        live work — the original wire request (so the adopter can
        rebuild its fleet-side handle or requeue).  Parked = a
        terminal result is being held for exactly-once claim."""
        self._stamp_resolved()
        self._sweep_journal()
        out = {}
        for rid, ent in self._journal.items():
            st = ent["state"]
            if st in ("resolved", "done"):
                st = "parked"
            out[rid] = {"state": st, "cursor": ent["cursor"],
                        "order": ent["order"],
                        "req": ent["req"] if st == "live" else None}
        return {"requests": out, "epoch": self._epoch}

    def op_claim(self, p):
        """Hand a PARKED terminal result to an adopting controller and
        forget it — exactly-once: the journal entry is deleted on
        claim, and a lost reply is covered by the seq-dedupe cache
        (the resend answers from memory, never re-executes)."""
        rid = p["rid"]
        self._stamp_resolved()
        ent = self._journal.get(rid)
        if ent is None:
            return {"status": "gone"}
        if ent["state"] == "expired":
            return {"status": "expired", "cursor": ent["cursor"]}
        if ent["state"] == "live":
            return {"status": "live"}
        out = ent["out"]
        if out is None:
            h, _req = self._handles.pop(rid)
            if h._error is not None:
                out = {"err": dump_exc(h._error)}
            else:
                out = {"result": self._dump_result(h._result)}
        else:
            self._handles.pop(rid, None)
        del self._journal[rid]
        # the claimed result carries the FULL token array; drop any
        # streamed-token backlog for this rid so it cannot ride a
        # later step reply into a controller that never submitted it
        self._tokens = [(r, t) for r, t in self._tokens if r != rid]
        return {"status": "parked", "out": out, "req": ent["req"],
                "cursor": ent["cursor"], "order": ent["order"]}

    def op_die(self, p):
        """Chaos/kill hook (one-way): stop WITHOUT redialing — a
        deliberately killed worker must stay dead, so thread-mode
        ``kill_worker`` sends this before closing its socket end
        (TCP ordering lands it ahead of the FIN)."""
        self._stop = True
        try:
            self.conn.close()
        except Exception:
            pass

    def op_shutdown(self, p):
        self._stop = True
        if self.sup is not None:
            try:
                self.sup.close(force=p.get("force", True))
            except Exception:
                pass
        return {}

    # -- disconnected mode ----------------------------------------------
    def _park_pass(self):
        """One disconnected-mode pass: keep stepping live work (a
        controller blip must never wedge decode mid-request), stamp
        newly finished handles ``resolved`` (results stay ON the
        handle so a same-controller resume drains them through the
        normal step reply), and sweep the park TTL."""
        if self.sup is not None:
            try:
                if self.sup.pending:
                    self.sup.step()
            except Exception:
                pass  # budget exhaustion resolves handles typed
        self._stamp_resolved()
        self._sweep_journal()

    def _reconnect(self) -> bool:
        """Bounded reconnect window: redial the controller address
        with full-jitter backoff, offering to RESUME this session
        (epoch + last executed seq).  Between attempts the engine
        keeps stepping (``_park_pass``).  Returns True with
        ``self.conn`` swapped on success; False when the budget is
        spent or the controller refuses (worker then dies and the
        fleet's failover owns the requests)."""
        host, port, token, idx = self._redial
        try:
            self.conn.close()
        except Exception:
            pass
        for attempt in range(self._reconnect_attempts):
            self._park_pass()
            try:
                conn, ack = resume_worker(
                    host, port, token, idx, self._epoch,
                    self._last_seq, timeout=self._redial_timeout)
            except (OSError, PeerGoneError, TransportError):
                time.sleep(_full_jitter(
                    self._rng, self._backoff_base, attempt,
                    self._backoff_cap))
                continue
            if not ack.get("ok") \
                    or ack.get("epoch", -1) < self._epoch:
                # an explicit refusal, or a controller offering an
                # OLDER epoch (the stale side of a split brain —
                # never downgrade the fence)
                conn.close()
                return False
            self._epoch = int(ack["epoch"])
            self.conn = conn
            return True
        return False

    def _lost_controller(self) -> bool:
        """Socket loss: True means give up (stop requested, reconnect
        disabled, or the redial budget spent)."""
        return (self._stop or self._redial is None
                or not self._reconnect())

    # -- loop ------------------------------------------------------------
    def run(self):
        while not self._stop:
            try:
                kind, msg = self.conn.recv(timeout=None)
            except (PeerGoneError, TransportError):
                if self._lost_controller():
                    break
                continue
            op = msg.get("op", "")
            # fencing: frames stamped with an epoch OLDER than the one
            # this worker last obeyed come from a deposed controller.
            # CALLs are refused typed (StaleEpochError crosses the
            # wire); one-ways are dropped — checked BEFORE dispatch so
            # every op is fenced by construction.
            ep = msg.get("epoch")
            if ep is not None and ep < self._epoch:
                if kind == MSG_CALL:
                    reply = {"seq": msg.get("seq"), "ok": False,
                             "err": dump_exc(StaleEpochError(
                                 f"epoch {ep} < fleet epoch "
                                 f"{self._epoch}: controller is "
                                 f"stale; op {op!r} refused"))}
                    try:
                        self.conn.send(MSG_REPLY, reply)
                    except PeerGoneError:
                        if self._lost_controller():
                            break
                continue
            handler = getattr(self, f"op_{op}", None)
            if kind == MSG_ONEWAY:
                if handler is not None:
                    try:
                        handler(msg.get("payload") or {})
                    except Exception:
                        pass  # one-ways are best-effort by contract
                continue
            if kind != MSG_CALL:
                continue
            seq = msg.get("seq")
            if seq == self._last_seq and self._last_reply is not None:
                # replayed seq after a resume: the call already ran,
                # only the reply was lost — answer from the cache
                # without re-executing (exactly-once)
                reply = self._last_reply
            else:
                if self._unacked is not None and seq is not None \
                        and seq > self._unacked[0]:
                    # a strictly newer call proves the reply carrying
                    # these terminal results landed: ack the journal
                    for rid in self._unacked[1]:
                        ent = self._journal.get(rid)
                        if ent is not None and ent["state"] == "done":
                            del self._journal[rid]
                    self._unacked = None
                if handler is None:
                    reply = {"seq": seq, "ok": False,
                             "err": dump_exc(
                                 RuntimeError(f"unknown op {op!r}"))}
                else:
                    try:
                        reply = {"seq": seq, "ok": True,
                                 "value": handler(msg.get("payload")
                                                  or {})}
                    except Exception as e:
                        reply = {"seq": seq, "ok": False,
                                 "err": dump_exc(e)}
                if seq is not None:
                    self._last_seq = seq
                    self._last_reply = reply
                if reply.get("ok") and isinstance(
                        reply.get("value"), dict):
                    rids = list((reply["value"].get("resolved")
                                 or {}).keys())
                    if rids:
                        self._unacked = (seq, rids)
            try:
                self.conn.send(MSG_REPLY, reply)
            except PeerGoneError:
                if self._lost_controller():
                    break
        # fleet gone or shutdown: release engine state (idempotent)
        if self.sup is not None and not self.sup.engine._closed:
            try:
                self.sup.close(force=True)
            except Exception:
                pass
        self.conn.close()


def worker_main(host, port, token, idx):
    """Process (or thread) entry point: dial the fleet, serve the
    command loop until shutdown or fleet loss — transient loss enters
    the bounded reconnect window instead of dying."""
    conn = connect_worker(host, port, token, idx)
    _Worker(conn, redial=(host, port, token, idx)).run()
